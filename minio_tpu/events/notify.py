"""Event notification engine.

Reference shape: internal/event/targetlist.go fan-out, webhook target
(internal/event/target/webhook.go) with a disk-backed retry store
(internal/store/queuestore.go). Rules come from the bucket notification
XML (PUT ?notification) with event-name wildcards and prefix/suffix
filter rules.
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET
from typing import Optional, Sequence

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
_NS = f"{{{XMLNS}}}"


class EventError(Exception):
    pass


@dataclasses.dataclass
class NotificationRule:
    events: list                 # e.g. ["s3:ObjectCreated:*"]
    prefix: str = ""
    suffix: str = ""
    target_id: str = "webhook"   # queue ARN tail

    def matches(self, event_name: str, key: str) -> bool:
        if not key.startswith(self.prefix) or not key.endswith(self.suffix):
            return False
        for pat in self.events:
            if pat == event_name or pat == "s3:*":
                return True
            if pat.endswith(":*") and event_name.startswith(pat[:-1]):
                return True
        return False


@dataclasses.dataclass
class NotificationConfig:
    rules: list = dataclasses.field(default_factory=list)


def parse_notification_xml(xml: bytes | str) -> NotificationConfig:
    """NotificationConfiguration XML -> config. QueueConfiguration
    entries map to webhook targets by the ARN's trailing id
    (arn:minio:sqs:<region>:<id>:webhook)."""
    try:
        root = ET.fromstring(xml)
    except ET.ParseError as e:
        raise EventError(f"malformed notification XML: {e}") from None
    # Strip namespaces once so every lookup below is plain-tag; clients
    # send both namespaced and bare documents.
    for el in root.iter():
        if isinstance(el.tag, str) and "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    cfg = NotificationConfig()
    for qel in root.iter("QueueConfiguration"):
        events = [e.text or "" for e in qel.findall("Event")]
        if not events:
            raise EventError("QueueConfiguration without Event")
        arn = qel.findtext("Queue") or ""
        # arn:minio:sqs:<region>:<id>:<target-type> — the trailing
        # component names the target kind registered with the notifier.
        target_id = arn.rsplit(":", 1)[-1] if arn else "webhook"
        prefix = suffix = ""
        for frel in qel.iter("FilterRule"):
            name = (frel.findtext("Name") or "").lower()
            value = frel.findtext("Value") or ""
            if name == "prefix":
                prefix = value
            elif name == "suffix":
                suffix = value
        cfg.rules.append(NotificationRule(events=events, prefix=prefix,
                                          suffix=suffix,
                                          target_id=target_id))
    return cfg


def make_event_record(event_name: str, bucket: str, key: str,
                      size: int = 0, etag: str = "",
                      version_id: str = "") -> dict:
    """S3 event message structure (reference: internal/event/event.go)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    return {
        "eventVersion": "2.1",
        "eventSource": "minio-tpu:s3",
        "awsRegion": "us-east-1",
        "eventTime": now.strftime("%Y-%m-%dT%H:%M:%S.%fZ"),
        "eventName": event_name,
        "s3": {
            "s3SchemaVersion": "1.0",
            "bucket": {"name": bucket,
                       "arn": f"arn:aws:s3:::{bucket}"},
            "object": {"key": urllib.parse.quote(key), "size": size,
                       "eTag": etag, "versionId": version_id,
                       "sequencer": format(time.time_ns(), "016x")},
        },
    }


class WebhookTarget:
    """POSTs event records as JSON to an HTTP endpoint."""

    def __init__(self, target_id: str, endpoint: str, timeout: float = 5.0):
        self.target_id = target_id
        self.endpoint = endpoint
        self.timeout = timeout

    def send(self, record: dict, wrap: bool = True) -> None:
        """POST one record; wrap=True uses the S3 event envelope
        ({"Records": [...]}), wrap=False posts the record bare (audit)."""
        body = json.dumps({"Records": [record]} if wrap else record).encode()
        req = urllib.request.Request(
            self.endpoint, data=body,
            headers={"Content-Type": "application/json",
                     "User-Agent": "minio-tpu-notify"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except urllib.error.HTTPError as e:
            # Non-2xx statuses surface as HTTPError, not via resp.status.
            raise EventError(f"webhook {self.endpoint}: {e.code}") from None


class EventNotifier:
    """Rules + targets + a disk-persisted store-and-forward queue.

    Undelivered events live as one JSON file each under store_dir
    (reference: internal/store/queuestore.go); the delivery worker
    retries with backoff, so a webhook outage delays notifications but
    never drops them. Rule lookups read the bucket's notification
    config through the object layer's bucket metadata."""

    _RETRY_BASE = 0.5
    _RETRY_MAX = 30.0

    def __init__(self, object_layer, store_dir: str,
                 targets: Optional[Sequence[WebhookTarget]] = None):
        self.object_layer = object_layer
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.targets = {t.target_id: t for t in (targets or [])}
        self._cfg_cache: dict = {}
        self.delivered = 0
        self.failed_attempts = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # -- rule resolution -------------------------------------------------

    def config_for(self, bucket: str) -> Optional[NotificationConfig]:
        try:
            doc = self.object_layer.get_bucket_meta(bucket) \
                .get("config:notification")
        except Exception:  # noqa: BLE001 - bucket gone
            return None
        if not doc:
            return None
        # Parse once per distinct document — this sits on the data path
        # of every mutating request (bucket meta itself is TTL-cached).
        hit = self._cfg_cache.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            cfg = parse_notification_xml(doc)
        except EventError:
            cfg = None
        self._cfg_cache[bucket] = (doc, cfg)
        return cfg

    # -- ingestion -------------------------------------------------------

    def notify(self, event_name: str, bucket: str, key: str,
               size: int = 0, etag: str = "", version_id: str = "") -> None:
        """Queue matching events; never blocks or raises into the data
        path."""
        try:
            cfg = self.config_for(bucket)
            if cfg is None:
                return
            record = None
            queued_targets = set()   # one event per TARGET, however
            for rule in cfg.rules:   # many rules match (reference dedup)
                if not rule.matches(event_name, key):
                    continue
                if rule.target_id not in self.targets \
                        or rule.target_id in queued_targets:
                    continue
                if record is None:
                    record = make_event_record(event_name, bucket, key,
                                               size, etag, version_id)
                queued_targets.add(rule.target_id)
                self._enqueue(rule.target_id, record)
        except Exception:  # noqa: BLE001 - notification is best-effort
            return

    def _enqueue(self, target_id: str, record: dict) -> None:
        name = f"{time.time_ns():020d}-{uuid.uuid4().hex[:8]}.json"
        tmp = os.path.join(self.store_dir, f".{name}.tmp")
        final = os.path.join(self.store_dir, name)
        with open(tmp, "w") as f:
            json.dump({"target": target_id, "record": record}, f)
        os.replace(tmp, final)
        self._wake.set()

    # -- delivery --------------------------------------------------------

    def _pending_files(self) -> list[str]:
        try:
            return sorted(f for f in os.listdir(self.store_dir)
                          if f.endswith(".json"))
        except FileNotFoundError:
            return []

    def _run(self) -> None:
        backoff = self._RETRY_BASE
        while not self._stop.is_set():
            files = self._pending_files()
            if not files:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            progressed = False
            for name in files:
                if self._stop.is_set():
                    return
                path = os.path.join(self.store_dir, name)
                try:
                    with open(path) as f:
                        entry = json.load(f)
                except (OSError, ValueError):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                target = self.targets.get(entry.get("target", ""))
                if target is None:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                try:
                    target.send(entry["record"])
                except Exception:  # noqa: BLE001 - retry after backoff
                    self.failed_attempts += 1
                    continue
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self.delivered += 1
                progressed = True
            if progressed:
                backoff = self._RETRY_BASE
            else:
                self._stop.wait(timeout=backoff)
                backoff = min(backoff * 2, self._RETRY_MAX)

    def drain(self, timeout: float = 10.0) -> bool:
        """Testing hook: wait until the store is empty."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if not self._pending_files():
                return True
            self._wake.set()
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout=2)
