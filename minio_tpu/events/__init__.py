"""Bucket event notification: rules, webhook targets, store-and-forward.

The analogue of the reference's event subsystem (internal/event/ +
internal/store/): buckets carry notification configurations (event-name
+ prefix/suffix filters), matching object operations produce
S3-format event records, and a store-and-forward queue delivers them to
webhook targets — persisting undelivered events to disk so target
downtime never loses notifications.
"""

from minio_tpu.events.notify import (EventNotifier, NotificationConfig,
                                     WebhookTarget, parse_notification_xml)
from minio_tpu.events.targets import (MQTTTarget, NATSTarget, RedisTarget,
                                      TargetError)

__all__ = ["EventNotifier", "NotificationConfig", "WebhookTarget",
           "MQTTTarget", "NATSTarget", "RedisTarget", "TargetError",
           "parse_notification_xml"]
