"""Additional event notification targets: MQTT, NATS, Redis.

The reference ships ten target types under internal/event/target/; the
webhook target (events/notify.py) covered one. These three speak their
REAL wire protocols from scratch (no client libraries in the image):

  MQTTTarget   MQTT 3.1.1 (OASIS spec): CONNECT/CONNACK handshake,
               QoS-1 PUBLISH awaiting PUBACK (internal/event/target/mqtt.go)
  NATSTarget   NATS text protocol: INFO/CONNECT/PUB/+OK
               (internal/event/target/nats.go)
  RedisTarget  RESP2: RPUSH of the event JSON onto a list key
               (internal/event/target/redis.go's list format)

All three plug into EventNotifier's store-and-forward queue, so a
broker outage delays delivery but never drops events; each send opens a
short-lived connection (the queue's cadence is sparse — holding idle
broker connections from every node buys nothing).
"""

from __future__ import annotations

import json
import socket


class TargetError(Exception):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TargetError("connection closed mid-frame")
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# MQTT 3.1.1
# ---------------------------------------------------------------------------

def _mqtt_string(s: bytes) -> bytes:
    return len(s).to_bytes(2, "big") + s


def _mqtt_remaining_len(n: int) -> bytes:
    """MQTT variable-length remaining-length encoding."""
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _mqtt_read_packet(sock) -> tuple[int, bytes]:
    """(packet type, payload) — decodes the variable-length header."""
    first = _recv_exact(sock, 1)[0]
    n = shift = 0
    while True:
        b = _recv_exact(sock, 1)[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 21:
            raise TargetError("malformed MQTT remaining length")
    return first >> 4, _recv_exact(sock, n) if n else b""


class MQTTTarget:
    """QoS-1 JSON publisher to an MQTT 3.1.1 broker."""

    def __init__(self, target_id: str, broker: str, topic: str,
                 timeout: float = 5.0, qos: int = 1):
        self.target_id = target_id
        host, _, port = broker.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.topic = topic
        self.timeout = timeout
        self.qos = 1 if qos else 0
        self._packet_id = 0

    def send(self, record: dict, wrap: bool = True) -> None:
        payload = json.dumps({"Records": [record]} if wrap
                             else record).encode()
        with socket.create_connection(self._addr,
                                      timeout=self.timeout) as s:
            # CONNECT: protocol "MQTT" level 4, clean session, no auth.
            var = (_mqtt_string(b"MQTT") + b"\x04" + b"\x02" +
                   (0).to_bytes(2, "big") +
                   _mqtt_string(b"minio-tpu-notify"))
            s.sendall(b"\x10" + _mqtt_remaining_len(len(var)) + var)
            ptype, body = _mqtt_read_packet(s)
            if ptype != 2 or len(body) < 2 or body[1] != 0:
                raise TargetError(f"MQTT CONNACK refused: {body!r}")
            # PUBLISH QoS1 (dup=0, retain=0).
            self._packet_id = (self._packet_id % 0xFFFF) + 1
            topic = _mqtt_string(self.topic.encode())
            if self.qos:
                var = topic + self._packet_id.to_bytes(2, "big") + payload
                s.sendall(bytes([0x30 | (self.qos << 1)]) +
                          _mqtt_remaining_len(len(var)) + var)
                ptype, body = _mqtt_read_packet(s)
                if ptype != 4 or body[:2] != \
                        self._packet_id.to_bytes(2, "big"):
                    raise TargetError("MQTT PUBACK missing/mismatched")
            else:
                var = topic + payload
                s.sendall(b"\x30" + _mqtt_remaining_len(len(var)) + var)
            s.sendall(b"\xe0\x00")          # DISCONNECT


# ---------------------------------------------------------------------------
# NATS
# ---------------------------------------------------------------------------

class NATSTarget:
    """PUBs the event JSON to a NATS subject (text protocol)."""

    def __init__(self, target_id: str, broker: str, subject: str,
                 timeout: float = 5.0):
        self.target_id = target_id
        host, _, port = broker.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.subject = subject
        self.timeout = timeout

    def send(self, record: dict, wrap: bool = True) -> None:
        payload = json.dumps({"Records": [record]} if wrap
                             else record).encode()
        with socket.create_connection(self._addr,
                                      timeout=self.timeout) as s:
            f = s.makefile("rb")
            info = f.readline()
            if not info.startswith(b"INFO "):
                raise TargetError(f"not a NATS server: {info[:40]!r}")
            s.sendall(b'CONNECT {"verbose":true,"pedantic":false,'
                      b'"name":"minio-tpu-notify","lang":"py",'
                      b'"version":"1"}\r\n')
            line = f.readline()
            if not line.startswith(b"+OK"):
                raise TargetError(f"NATS CONNECT refused: {line[:40]!r}")
            s.sendall(b"PUB " + self.subject.encode() + b" " +
                      str(len(payload)).encode() + b"\r\n" +
                      payload + b"\r\n")
            line = f.readline()
            if not line.startswith(b"+OK"):
                raise TargetError(f"NATS PUB refused: {line[:40]!r}")


# ---------------------------------------------------------------------------
# Redis (RESP2)
# ---------------------------------------------------------------------------

class RedisTarget:
    """RPUSHes the event JSON onto a Redis list key."""

    def __init__(self, target_id: str, broker: str, key: str,
                 timeout: float = 5.0, password: str = ""):
        self.target_id = target_id
        host, _, port = broker.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.key = key
        self.timeout = timeout
        self.password = password

    @staticmethod
    def _cmd(*parts: bytes) -> bytes:
        out = b"*" + str(len(parts)).encode() + b"\r\n"
        for p in parts:
            out += b"$" + str(len(p)).encode() + b"\r\n" + p + b"\r\n"
        return out

    @staticmethod
    def _reply(f) -> bytes:
        line = f.readline()
        if not line:
            raise TargetError("redis closed the connection")
        if line[:1] == b"-":
            raise TargetError(f"redis error: {line[1:].strip().decode()}")
        return line

    def send(self, record: dict, wrap: bool = True) -> None:
        payload = json.dumps({"Records": [record]} if wrap
                             else record).encode()
        with socket.create_connection(self._addr,
                                      timeout=self.timeout) as s:
            f = s.makefile("rb")
            if self.password:
                s.sendall(self._cmd(b"AUTH", self.password.encode()))
                self._reply(f)
            s.sendall(self._cmd(b"RPUSH", self.key.encode(), payload))
            self._reply(f)
