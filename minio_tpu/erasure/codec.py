"""Erasure codec: systematic Reed-Solomon over GF(2^8), pluggable backend.

Mirrors the reference's codec semantics exactly (reference:
cmd/erasure-coding.go:35-144): same coding matrix family, same Split padding
(per-shard length = ceil(len/k), zero padded), same ShardSize /
ShardFileSize / ShardFileOffset math — so encoded shards are byte-identical
to the reference's and the golden self-test digests pass
(cmd/erasure-coding.go:163).

The GF "matmul" itself goes through a pluggable backend so the object /
multipart / healing layers never care where the math runs:
  - HostBackend: numpy table lookups (always available; used for tiny
    blocks where a device round-trip is not worth it)
  - the TPU backend in minio_tpu/ops/rs_device.py: bitplane decomposition +
    MXU matmul, batched over whole stripe batches.
"""

from __future__ import annotations

from typing import Optional, Protocol, Sequence

import numpy as np

from minio_tpu.ops import gf256


def ceil_frac(numerator: int, denominator: int) -> int:
    """Go-style ceilFrac (reference: cmd/utils.go ceilFrac)."""
    if denominator == 0:
        return 0
    return (numerator + denominator - 1) // denominator


class ECBackend(Protocol):
    """The seam behind which the math runs (host SIMD-ish numpy or TPU)."""

    def apply_matrix(self, matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """out[r] = XOR_j matrix[r, j] * shards[j] over GF(2^8).

        matrix: uint8 [r, k]; shards: uint8 [k, shard_len] -> [r, shard_len].
        """
        ...


class HostBackend:
    """Host GF path: native C++ nibble-split kernel when built (the
    analogue of the reference's assembly Galois kernels), numpy tables
    otherwise. Both byte-identical."""

    def apply_matrix(self, matrix: np.ndarray, shards: np.ndarray) -> np.ndarray:
        from minio_tpu import native
        lib = native.load()
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if lib is not None and shards.size:
            r, k = matrix.shape
            length = shards.shape[1]
            out = np.empty((r, length), dtype=np.uint8)
            lib.mtpu_gf_apply(native._u8(matrix), r, k, native._u8(shards),
                              length, length, native._u8(out), length)
            return out
        return gf256.gf_matvec_bytes(matrix, shards)


_HOST = HostBackend()


class Erasure:
    """Erasure coding details for one (k, m, block_size) configuration."""

    def __init__(self, data_blocks: int, parity_blocks: int, block_size: int,
                 backend: Optional[ECBackend] = None):
        if data_blocks <= 0 or parity_blocks < 0:
            raise ValueError("invalid shard counts")
        if data_blocks + parity_blocks > 256:
            raise ValueError("too many shards for GF(2^8)")
        self.data_blocks = data_blocks
        self.parity_blocks = parity_blocks
        self.block_size = block_size
        self.backend: ECBackend = backend if backend is not None else _HOST

    # -- shard-size math (byte-compatible with the reference) ---------------

    def shard_size(self) -> int:
        """Shard size of a full erasure block."""
        return ceil_frac(self.block_size, self.data_blocks)

    def shard_file_size(self, total_length: int) -> int:
        """On-disk size of one shard file for an object of total_length."""
        if total_length == 0:
            return 0
        if total_length == -1:
            return -1
        num_blocks = total_length // self.block_size
        last_block = total_length % self.block_size
        last_shard = ceil_frac(last_block, self.data_blocks)
        return num_blocks * self.shard_size() + last_shard

    def shard_file_offset(self, start_offset: int, length: int, total_length: int) -> int:
        """Shard-file offset up to which reads must proceed for a range."""
        shard_size = self.shard_size()
        shard_file_size = self.shard_file_size(total_length)
        end_shard = (start_offset + length) // self.block_size
        till = end_shard * shard_size + shard_size
        return min(till, shard_file_size)

    # -- encode -------------------------------------------------------------

    def split(self, data: bytes | bytearray | memoryview | np.ndarray) -> np.ndarray:
        """Split input into k zero-padded data shards: uint8 [k, per_shard]."""
        buf = data.astype(np.uint8, copy=False).reshape(-1) if isinstance(data, np.ndarray) \
            else np.frombuffer(data, dtype=np.uint8)
        if buf.size == 0:
            raise ValueError("short data")
        k = self.data_blocks
        per_shard = ceil_frac(buf.size, k)
        padded = np.zeros(k * per_shard, dtype=np.uint8)
        padded[:buf.size] = buf
        return padded.reshape(k, per_shard)

    def encode_data(self, data: bytes | bytearray | memoryview | np.ndarray) -> list[np.ndarray]:
        """Encode one block: returns k+m shards, each uint8 [per_shard].

        Empty input returns k+m empty placeholders (reference:
        cmd/erasure-coding.go:77-79).
        """
        n = self.data_blocks + self.parity_blocks
        if isinstance(data, np.ndarray):
            empty = data.size == 0
        else:
            empty = len(data) == 0
        if empty:
            return [np.zeros(0, dtype=np.uint8) for _ in range(n)]
        data_shards = self.split(data)
        if self.parity_blocks == 0:
            return list(data_shards)
        pm = gf256.parity_matrix(self.data_blocks, self.parity_blocks)
        parity = self.backend.apply_matrix(pm, data_shards)
        return list(data_shards) + list(np.asarray(parity))

    # -- decode / reconstruct ----------------------------------------------

    def _reconstruct(self, shards: list[Optional[np.ndarray]], data_only: bool) -> None:
        """Fill missing entries of `shards` in place from k survivors."""
        k, m = self.data_blocks, self.parity_blocks
        n = k + m
        if len(shards) != n:
            raise ValueError(f"expected {n} shards, got {len(shards)}")

        present = [i for i, s in enumerate(shards) if s is not None and s.size > 0]
        if len(present) == n:
            return
        if len(present) < k:
            raise ReconstructError(
                f"too few shards: {len(present)} of {n}, need {k}")
        shard_len = shards[present[0]].shape[0]
        for i in present:
            if shards[i].shape[0] != shard_len:
                raise ShardSizeError("shard size mismatch")

        # Use the first k surviving shards, like the reference's dependency.
        use = tuple(present[:k])
        missing_data = [i for i in range(k)
                        if shards[i] is None or shards[i].size == 0]
        if missing_data:
            dec = gf256.decode_matrix(k, m, use)
            inputs = np.stack([shards[i] for i in use])
            rows = dec[missing_data, :]
            out = np.asarray(self.backend.apply_matrix(rows, inputs))
            for row, i in enumerate(missing_data):
                shards[i] = out[row]
        if data_only:
            return
        missing_parity = [i for i in range(k, n)
                          if shards[i] is None or shards[i].size == 0]
        if missing_parity:
            pm = gf256.parity_matrix(k, m)
            rows = pm[[i - k for i in missing_parity], :]
            data_stack = np.stack([shards[i] for i in range(k)])
            out = np.asarray(self.backend.apply_matrix(rows, data_stack))
            for row, i in enumerate(missing_parity):
                shards[i] = out[row]

    def decode_data_blocks(self, shards: list[Optional[np.ndarray]]) -> None:
        """Reconstruct only the data shards (reference: DecodeDataBlocks).

        No-op when no shard is missing, or for the degenerate single-shard
        case. All-empty with n > 1 raises ReconstructError — total loss
        must surface as a read-quorum error, never as silent success
        (matches the reference, whose early-return is only reachable for
        n == 1 because its zero-scan breaks on the first empty shard).
        """
        missing = any(s is None or s.size == 0 for s in shards)
        if not missing or len(shards) == 1:
            return
        self._reconstruct(shards, data_only=True)

    def decode_data_and_parity_blocks(self, shards: list[Optional[np.ndarray]]) -> None:
        """Reconstruct all shards (reference: DecodeDataAndParityBlocks)."""
        self._reconstruct(shards, data_only=False)

    def join(self, shards: Sequence[np.ndarray], out_size: int) -> bytes:
        """Concatenate data shards and trim padding to out_size bytes."""
        k = self.data_blocks
        flat = np.concatenate([np.asarray(s, dtype=np.uint8) for s in shards[:k]])
        return flat[:out_size].tobytes()


class CodecError(Exception):
    """Base for erasure-codec data errors (callers map these to quorum
    errors / heal triggers, never to crashes)."""


class ReconstructError(CodecError):
    """Too few shards to reconstruct (maps to errErasureReadQuorum)."""


class ShardSizeError(CodecError):
    """A surviving shard has the wrong length (truncated/corrupt read)."""
