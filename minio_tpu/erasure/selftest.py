"""Boot-time self-tests with the reference's golden digests.

Replicates the reference's erasureSelfTest (cmd/erasure-coding.go:152-209):
for every (data, parity) config with total in [4, 16) and data in
[total/2, total), encode the 256-byte staircase vector and check the
xxhash64 digest of `index byte || shard` concatenations against golden
values copied from the reference, then delete the first shard and verify
reconstruction round-trips byte-identically. A failure means the codec
would corrupt data — callers must treat it as fatal, exactly like the
reference server does at startup (cmd/server-main.go:799-803).
"""

from __future__ import annotations

import numpy as np

from minio_tpu.erasure.codec import Erasure
from minio_tpu.utils.xxh64 import xxh64

BLOCK_SIZE_V2 = 1024 * 1024  # reference: cmd/object-api-common.go:37

# Golden xxhash64 digests, copied from cmd/erasure-coding.go:163.
GOLDEN_ERASURE_DIGESTS: dict[tuple[int, int], int] = {
    (2, 2): 0x23FB21BE2496F5D3, (2, 3): 0xA5CD5600BA0D8E7C,
    (3, 1): 0x60AB052148B010B4, (3, 2): 0xE64927DAEF76435A,
    (3, 3): 0x672F6F242B227B21, (3, 4): 0x0571E41BA23A6DC6,
    (4, 1): 0x524EAA814D5D86E2, (4, 2): 0x62B9552945504FEF,
    (4, 3): 0xCBF9065EE053E518, (4, 4): 0x09A07581DCD03DA8,
    (4, 5): 0xBF2D27B55370113F, (5, 1): 0x0F71031A01D70DAF,
    (5, 2): 0x8E5845859939D0F4, (5, 3): 0x7AD9161ACBB4C325,
    (5, 4): 0xC446B88830B4F800, (5, 5): 0xABF1573CC6F76165,
    (5, 6): 0x7B5598A85045BFB8, (6, 1): 0xE2FC1E677CC7D872,
    (6, 2): 0x7ED133DE5CA6A58E, (6, 3): 0x39EF92D0A74CC3C0,
    (6, 4): 0x0CFC90052BC25D20, (6, 5): 0x71C96F6BAEEF9C58,
    (6, 6): 0x4B79056484883E4C, (6, 7): 0xB1A0E2427AC2DC1A,
    (7, 1): 0x937BA2B7AF467A22, (7, 2): 0x5FD13A734D27D37A,
    (7, 3): 0x3BE2722D9B66912F, (7, 4): 0x14C628E59011BE3D,
    (7, 5): 0xCC3B39AD4C083B9F, (7, 6): 0x45AF361B7DE7A4FF,
    (7, 7): 0x456CC320CEC8A6E6, (7, 8): 0x1867A9F4DB315B5C,
    (8, 1): 0xBC5756B9A9ADE030, (8, 2): 0xDFD7D9D0B3E36503,
    (8, 3): 0x72BB72C2CDBCF99D, (8, 4): 0x03BA5E9B41BF07F0,
    (8, 5): 0xD7DABC15800F9D41, (8, 6): 0x0B482A6169FD270F,
    (8, 7): 0x50748E0099D657E8, (9, 1): 0xC77AE0144FCAEB6E,
    (9, 2): 0x8A86C7DBEBF27B68, (9, 3): 0xA64E3BE6D6FE7E92,
    (9, 4): 0x239B71C41745D207, (9, 5): 0x2D0803094C5A86CE,
    (9, 6): 0xA3C2539B3AF84874, (10, 1): 0x7D30D91B89FCEC21,
    (10, 2): 0xFA5AF9AA9F1857A3, (10, 3): 0x84BC4BDA8AF81F90,
    (10, 4): 0x6C1CBA8631DE994A, (10, 5): 0x4383E58A086CC1AC,
    (11, 1): 0x04ED2929A2DF690B, (11, 2): 0xECD6F1B1399775C0,
    (11, 3): 0xC78CFBFC0DC64D01, (11, 4): 0xB2643390973702D6,
    (12, 1): 0x3B2A88686122D082, (12, 2): 0x0FD2F30A48A8E2E9,
    (12, 3): 0xD5CE58368AE90B13, (13, 1): 0x9C88E2A9D1B8FFF8,
    (13, 2): 0x0CB8460AA4CF6613, (14, 1): 0x78A28BBAEC57996E,
}


class SelfTestError(Exception):
    """The codec produced bytes that differ from the reference. Fatal."""


def erasure_self_test(backend=None) -> None:
    """Hard-fails (raises) unless the codec is byte-identical to the reference."""
    if set(self_test_configs()) != set(GOLDEN_ERASURE_DIGESTS):
        raise SelfTestError("golden digest table does not cover the reference sweep")
    test_data = bytes(range(256))
    for (data, parity), want in GOLDEN_ERASURE_DIGESTS.items():
        e = Erasure(data, parity, BLOCK_SIZE_V2, backend=backend)
        encoded = e.encode_data(test_data)
        buf = bytearray()
        for i, shard in enumerate(encoded):
            buf.append(i)
            buf.extend(shard.tobytes())
        got = xxh64(bytes(buf))
        if got != want:
            raise SelfTestError(
                f"erasure self-test [d:{data},p:{parity}]: "
                f"want {want:#x}, got {got:#x}")
        # Delete the first shard and reconstruct.
        first = encoded[0].copy()
        encoded[0] = np.zeros(0, dtype=np.uint8)
        e.decode_data_blocks(encoded)
        if not np.array_equal(first, encoded[0]):
            raise SelfTestError(
                f"erasure self-test [d:{data},p:{parity}]: reconstruct mismatch")


def self_test_configs() -> list[tuple[int, int]]:
    """The (data, parity) sweep the reference tests: total in [4,16)."""
    configs = []
    for total in range(4, 16):
        for data in range(total // 2, total):
            configs.append((data, total - data))
    return configs
