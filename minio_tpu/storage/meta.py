"""Object metadata: the per-object version journal ("xl.meta" analogue).

The reference keeps one small metadata file next to each object's shard
data holding a journal of versions (objects, delete markers), erasure
layout, part list, and — for small objects — the shard bytes inline
(reference: cmd/xl-storage-format-v2.go:42-88, cmd/storage-datatypes.go:191,
cmd/xl-storage-meta-inline.go). We keep those semantics — version
journal, latest-first ordering, delete markers, inline data, per-version
data dirs — with our own msgpack layout (no byte-level format
compatibility is needed; quorum comparison happens on parsed values).

File layout: 4-byte magic ``XTP1`` + msgpack map:
  {"versions": [version-map, ...], "inline": {version_id: bytes}}
Versions are stored sorted by (mod_time, version_id) descending, so
index 0 is the latest — same invariant the reference maintains.
"""

from __future__ import annotations

import dataclasses
import time
import uuid as uuid_mod
from typing import Optional

import msgpack

MAGIC = b"XTP1"

# Version kinds (reference: object / delete-marker / legacy journal entries,
# cmd/xl-storage-format-v2.go:73-88).
KIND_OBJECT = 1
KIND_DELETE_MARKER = 2

NULL_VERSION_ID = "null"


def new_uuid() -> str:
    return str(uuid_mod.uuid4())


def now_ns() -> int:
    return time.time_ns()


@dataclasses.dataclass
class ErasureInfo:
    """Per-disk erasure layout of one version (reference: ErasureInfo,
    cmd/storage-datatypes.go; checksums cover the bitrot algorithm per part)."""
    algorithm: str = "rs-vandermonde"
    data_blocks: int = 0
    parity_blocks: int = 0
    block_size: int = 0
    index: int = 0               # 1-based shard index held by this disk
    distribution: tuple[int, ...] = ()
    checksums: list[dict] = dataclasses.field(default_factory=list)

    def to_map(self) -> dict:
        return {
            "alg": self.algorithm, "k": self.data_blocks,
            "m": self.parity_blocks, "bs": self.block_size,
            "idx": self.index, "dist": list(self.distribution),
            "cks": self.checksums,
        }

    @classmethod
    def from_map(cls, m: dict) -> "ErasureInfo":
        return cls(algorithm=m.get("alg", ""), data_blocks=m.get("k", 0),
                   parity_blocks=m.get("m", 0), block_size=m.get("bs", 0),
                   index=m.get("idx", 0),
                   distribution=tuple(m.get("dist", ())),
                   checksums=list(m.get("cks", ())))

    def shard_size(self) -> int:
        from minio_tpu.erasure.codec import ceil_frac
        return ceil_frac(self.block_size, self.data_blocks)

    def shard_file_size(self, total: int) -> int:
        from minio_tpu.erasure.codec import Erasure
        return Erasure(self.data_blocks, self.parity_blocks,
                       self.block_size).shard_file_size(total)


@dataclasses.dataclass
class ObjectPartInfo:
    number: int
    size: int                    # on-wire (possibly compressed/encrypted) size
    actual_size: int             # original client payload size
    mod_time: int = 0
    etag: str = ""
    # SSE multipart: this part's DARE base nonce, base64 (fresh random
    # per upload ATTEMPT — a re-uploaded part must never reuse AES-GCM
    # (key, nonce) pairs on different plaintext). "" for plain parts.
    nonce: str = ""

    def to_map(self) -> dict:
        m = {"n": self.number, "s": self.size, "as": self.actual_size,
             "mt": self.mod_time, "etag": self.etag}
        if self.nonce:
            m["nc"] = self.nonce
        return m

    @classmethod
    def from_map(cls, m: dict) -> "ObjectPartInfo":
        return cls(number=m["n"], size=m["s"], actual_size=m.get("as", m["s"]),
                   mod_time=m.get("mt", 0), etag=m.get("etag", ""),
                   nonce=m.get("nc", ""))


@dataclasses.dataclass
class FileInfo:
    """One version of one object as seen by one disk (reference: FileInfo,
    cmd/storage-datatypes.go:191). This is the unit quorum logic compares."""
    volume: str = ""
    name: str = ""
    version_id: str = ""         # "" == null version
    is_latest: bool = True
    deleted: bool = False        # delete marker
    data_dir: str = ""
    mod_time: int = 0            # ns since epoch
    size: int = 0
    metadata: dict = dataclasses.field(default_factory=dict)
    parts: list[ObjectPartInfo] = dataclasses.field(default_factory=list)
    erasure: ErasureInfo = dataclasses.field(default_factory=ErasureInfo)
    inline_data: Optional[bytes] = None
    fresh: bool = False          # first write of this object path
    successor_mod_time: int = 0

    def storage_version_id(self) -> str:
        return self.version_id or NULL_VERSION_ID

    def to_version_map(self) -> dict:
        v = {
            "kind": KIND_DELETE_MARKER if self.deleted else KIND_OBJECT,
            "vid": self.storage_version_id(),
            "mt": self.mod_time,
        }
        if not self.deleted:
            v.update({
                "ddir": self.data_dir, "size": self.size,
                "meta": dict(self.metadata),
                "parts": [p.to_map() for p in self.parts],
                "ec": self.erasure.to_map(),
                "inline": self.inline_data is not None,
            })
        else:
            v["meta"] = dict(self.metadata)
        return v


def fi_to_wire(fi: "FileInfo") -> dict:
    """Full FileInfo <-> msgpack map for the grid RPC mesh (the analogue
    of the reference's msgp-generated FileInfo codec,
    cmd/storage-datatypes_gen.go)."""
    return {
        "vol": fi.volume, "name": fi.name, "vid": fi.version_id,
        "lat": fi.is_latest, "del": fi.deleted, "ddir": fi.data_dir,
        "mt": fi.mod_time, "size": fi.size, "meta": dict(fi.metadata),
        "parts": [p.to_map() for p in fi.parts], "ec": fi.erasure.to_map(),
        "inl": fi.inline_data, "fresh": fi.fresh,
        "smt": fi.successor_mod_time,
    }


def fi_from_wire(d: dict) -> "FileInfo":
    return FileInfo(
        volume=d.get("vol", ""), name=d.get("name", ""),
        version_id=d.get("vid", ""), is_latest=d.get("lat", True),
        deleted=d.get("del", False), data_dir=d.get("ddir", ""),
        mod_time=d.get("mt", 0), size=d.get("size", 0),
        metadata=dict(d.get("meta", {})),
        parts=[ObjectPartInfo.from_map(p) for p in d.get("parts", ())],
        erasure=ErasureInfo.from_map(d.get("ec", {})),
        inline_data=d.get("inl"), fresh=d.get("fresh", False),
        successor_mod_time=d.get("smt", 0),
    )


class MetaError(Exception):
    pass


class FileNotFoundErr(MetaError):
    pass


class VersionNotFoundErr(MetaError):
    pass


class MethodNotAllowedErr(MetaError):
    """Read of a delete marker (maps to S3 MethodNotAllowed)."""


class XLMeta:
    """The parsed version journal of one object path on one disk."""

    def __init__(self) -> None:
        self.versions: list[dict] = []        # sorted latest-first
        self.inline: dict[str, bytes] = {}    # version_id -> shard bytes

    # -- serialization ------------------------------------------------------

    def dump(self) -> bytes:
        return MAGIC + msgpack.packb(
            {"versions": self.versions, "inline": self.inline},
            use_bin_type=True)

    @classmethod
    def load(cls, blob: bytes) -> "XLMeta":
        if len(blob) < 4 or blob[:4] != MAGIC:
            raise MetaError("bad object metadata magic")
        m = msgpack.unpackb(blob[4:], raw=False, strict_map_key=False)
        x = cls()
        x.versions = list(m.get("versions", ()))
        x.inline = {k: v for k, v in m.get("inline", {}).items()}
        return x

    # -- journal ops --------------------------------------------------------

    def _sort(self) -> None:
        self.versions.sort(key=lambda v: (v["mt"], v["vid"]), reverse=True)

    def add_version(self, fi: FileInfo) -> str:
        """Insert/replace a version. Returns the replaced entry's data_dir
        ("" if none) so callers can reclaim its shard files — overwriting
        the null version must not leak the old data dir."""
        vid = fi.storage_version_id()
        old = self._find(vid)
        old_ddir = ""
        if old is not None:
            self.versions.remove(old)
            self.inline.pop(vid, None)
            old_ddir = old.get("ddir", "") or ""
        self.versions.append(fi.to_version_map())
        if fi.inline_data is not None:
            self.inline[vid] = bytes(fi.inline_data)
        self._sort()
        if old_ddir and old_ddir != fi.data_dir and \
                self.shared_data_dir_count(vid, old_ddir) == 0:
            return old_ddir
        return ""

    def version_unchanged(self, fi: FileInfo) -> bool:
        """True when add_version(fi) would be a byte-identical no-op:
        the resident entry for this version id equals fi's version map
        AND its inline bytes. Overwrite-with-same-content storms (MRF
        retries, replication resync, heal rewrites of agreeing copies
        — anything that preserves mod_time) then skip the full journal
        rewrite + fsync entirely."""
        vid = fi.storage_version_id()
        old = self._find(vid)
        if old is None or old != fi.to_version_map():
            return False
        want = bytes(fi.inline_data) if fi.inline_data is not None \
            else None
        return self.inline.get(vid) == want

    def delete_version(self, version_id: str) -> str:
        """Remove a version; returns its data_dir ("" if none/inline)."""
        vid = version_id or NULL_VERSION_ID
        v = self._find(vid)
        if v is None:
            raise VersionNotFoundErr(vid)
        self.versions.remove(v)
        self.inline.pop(vid, None)
        return v.get("ddir", "") if not v.get("inline") else ""

    def _find(self, vid: str) -> Optional[dict]:
        for v in self.versions:
            if v["vid"] == vid:
                return v
        return None

    def latest(self) -> Optional[dict]:
        return self.versions[0] if self.versions else None

    def to_fileinfo(self, volume: str, name: str, version_id: str = "",
                    read_data: bool = False) -> FileInfo:
        """Resolve a version (default: latest) into a FileInfo.

        Mirrors the reference's ToFileInfo: requesting the latest version
        of an object whose latest is a delete marker yields deleted=True;
        requesting a specific missing version raises VersionNotFound.
        """
        if not self.versions:
            raise FileNotFoundErr(f"{volume}/{name}")
        if version_id:
            v = self._find(version_id)
            if v is None:
                raise VersionNotFoundErr(version_id)
        else:
            v = self.versions[0]
        return self._map_to_fileinfo(v, volume, name, read_data)

    def _map_to_fileinfo(self, v: dict, volume: str, name: str,
                         read_data: bool) -> FileInfo:
        vid = v["vid"]
        fi = FileInfo(
            volume=volume, name=name,
            version_id="" if vid == NULL_VERSION_ID else vid,
            is_latest=(self.versions and self.versions[0] is v),
            deleted=v["kind"] == KIND_DELETE_MARKER,
            mod_time=v["mt"],
        )
        if fi.deleted:
            fi.metadata = dict(v.get("meta", {}))
            return fi
        fi.data_dir = v.get("ddir", "")
        fi.size = v.get("size", 0)
        fi.metadata = dict(v.get("meta", {}))
        fi.parts = [ObjectPartInfo.from_map(p) for p in v.get("parts", ())]
        fi.erasure = ErasureInfo.from_map(v.get("ec", {}))
        if v.get("inline") and read_data:
            fi.inline_data = self.inline.get(vid)
        elif v.get("inline"):
            fi.inline_data = b""  # marker: data is inline, not loaded
        return fi

    def list_versions(self, volume: str, name: str) -> list[FileInfo]:
        return [self._map_to_fileinfo(v, volume, name, read_data=False)
                for v in self.versions]

    def shared_data_dir_count(self, vid: str, data_dir: str) -> int:
        """How many OTHER versions reference data_dir (reference keeps a
        refcount so remaps/copies can share a data dir)."""
        return sum(1 for v in self.versions
                   if v.get("ddir") == data_dir and v["vid"] != vid)
