"""Drive health wrapper: latency tracking, op deadlines, circuit breaker.

The analogue of the reference's xlStorageDiskIDCheck wrapper
(cmd/xl-storage-disk-id-check.go): every StorageAPI call is timed and
deadline-bounded, consecutive infrastructure faults (timeouts, I/O
errors) trip a breaker that fails calls FAST while the drive is
considered offline, and a half-open probe re-admits it after a
cooldown. Quorum fan-outs over wrapped drives therefore stay bounded in
latency even when a drive hangs rather than dies — the failure mode
plain error handling never catches.

Domain errors (missing files/volumes, corrupt journals) are the
storage layer working CORRECTLY and never count against the drive.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

from minio_tpu.storage.local import (DiskAccessDenied, FaultyDisk,
                                     VolumeExists, VolumeNotEmpty,
                                     VolumeNotFound)
from minio_tpu.storage.meta import (FileNotFoundErr, MetaError,
                                    VersionNotFoundErr)
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import DeadlineExceeded

# Errors that mean "the drive answered correctly" — never breaker fuel.
# The BUILTIN FileNotFoundError is deliberately absent: LocalStorage
# converts every ordinary missing-object case to FileNotFoundErr, so a
# raw one means the drive root itself vanished (unmounted disk) — the
# reference maps that to disk-not-found, and so does this breaker.
_DOMAIN_ERRORS = (FileNotFoundErr, VersionNotFoundErr, MetaError,
                  VolumeNotFound, VolumeExists, VolumeNotEmpty,
                  DiskAccessDenied, IsADirectoryError,
                  NotADirectoryError, ValueError, KeyError)

# Bulk transfer ops get a longer deadline than metadata ops.
# commit_group is bulk: one call commits a whole coalesced batch (many
# members' journals + one WAL fsync) and must not be clipped by the
# single-op metadata timeout.
_BULK_OPS = {"create_file", "read_file", "rename_data", "commit_group"}
# Ops returning lazy iterators: each next() must go through the
# deadline/breaker machinery, not just the (instant) generator creation.
_GENERATOR_OPS = {"walk_dir", "walk_scan"}


class _DaemonPool:
    """Minimal executor with DAEMON workers: a call hung on dead storage
    must never block interpreter shutdown (ThreadPoolExecutor joins its
    workers at exit)."""

    def __init__(self, workers: int):
        self._q: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self._threads = [threading.Thread(target=self._work, daemon=True)
                         for _ in range(workers)]
        for t in self._threads:
            t.start()

    def submit(self, fn, *args, **kwargs) -> Future:
        f: Future = Future()
        self._q.put((f, fn, args, kwargs))
        return f

    def _work(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            f, fn, args, kwargs = item
            if not f.set_running_or_notify_cancel():
                continue
            try:
                f.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 - ferried to caller
                f.set_exception(e)

    def shutdown(self) -> None:
        for _ in self._threads:
            self._q.put(None)


class DiskHealthWrapper:
    """Wraps any StorageAPI-shaped drive with deadlines + a breaker.

    op_timeout / bulk_timeout: per-call deadlines (seconds).
    trip_after: consecutive faults that open the breaker.
    cooldown: seconds the breaker stays open before a half-open probe.
    """

    def __init__(self, disk, op_timeout: float = 10.0,
                 bulk_timeout: float = 120.0, trip_after: int = 3,
                 cooldown: float = 5.0):
        self._disk = disk
        self._op_timeout = op_timeout
        self._bulk_timeout = bulk_timeout
        self._trip_after = trip_after
        self._cooldown = cooldown
        self._mu = threading.Lock()
        self._consecutive = 0
        self._open_since: float = 0.0     # 0 = closed
        self._half_open_probe = False
        # Consecutive budget-clamped expiries with a GENEROUS window
        # (see _SUSPICION_WINDOW): ambiguous individually, but a drive
        # that repeatedly cannot answer inside whole seconds is hung —
        # without this, any request deadline shorter than the op
        # timeout would classify every expiry as "the request's
        # problem" and a dead drive could never trip the breaker.
        self._clamped_streak = 0
        # op -> [count, errors, total_seconds]; small and bounded.
        self.op_stats: dict[str, list] = {}
        # A hung call occupies a worker until it returns; the breaker
        # stops new submissions long before the pool exhausts.
        self._pool = _DaemonPool(workers=8)

    # -- introspection ---------------------------------------------------

    @property
    def wrapped(self):
        return self._disk

    @property
    def endpoint(self):
        return getattr(self._disk, "endpoint", "")

    @property
    def root(self):
        return getattr(self._disk, "root", None)

    def is_online(self) -> bool:
        with self._mu:
            return self._open_since == 0.0

    def health_info(self) -> dict:
        with self._mu:
            return {
                "online": self._open_since == 0.0,
                "consecutive_faults": self._consecutive,
                "ops": {op: {"count": s[0], "errors": s[1],
                             "avg_ms": round(1000 * s[2] / s[0], 3)
                             if s[0] else 0.0}
                        for op, s in self.op_stats.items()},
            }

    # -- call path -------------------------------------------------------

    def _admit(self) -> None:
        """Fail fast while the breaker is open; let one probe through
        after the cooldown (half-open)."""
        with self._mu:
            if self._open_since == 0.0:
                return
            if time.monotonic() - self._open_since < self._cooldown:
                raise FaultyDisk(f"drive {self.endpoint}: breaker open")
            if self._half_open_probe:
                raise FaultyDisk(
                    f"drive {self.endpoint}: breaker half-open, probing")
            self._half_open_probe = True

    def _record(self, op: str, seconds: float, failed: bool) -> None:
        with self._mu:
            s = self.op_stats.setdefault(op, [0, 0, 0.0])
            s[0] += 1
            s[1] += 1 if failed else 0
            s[2] += seconds

    def _fault(self) -> None:
        with self._mu:
            self._consecutive += 1
            self._half_open_probe = False
            if self._open_since != 0.0:
                # Failed half-open probe: restart the cooldown, or every
                # request after the first expiry would become a probe
                # and eat the full op timeout.
                self._open_since = time.monotonic()
            elif self._consecutive >= self._trip_after:
                self._open_since = time.monotonic()

    def _ok(self) -> None:
        with self._mu:
            self._consecutive = 0
            self._clamped_streak = 0
            self._open_since = 0.0
            self._half_open_probe = False

    # Clamped expiries only count toward suspicion when the drive had
    # at least this long to answer — a request with 50 ms left proves
    # nothing, but whole seconds of silence repeated trip_after times
    # in a row does.
    _SUSPICION_WINDOW = 1.0

    def _clamped_expiry(self, window: float) -> None:
        """A budget-clamped op expiry: release the probe slot, and
        accumulate generous-window expiries; a full streak is treated
        as a real fault episode and opens the breaker outright."""
        with self._mu:
            self._half_open_probe = False
            if window < self._SUSPICION_WINDOW:
                return
            self._clamped_streak += 1
            if self._clamped_streak >= self._trip_after:
                self._clamped_streak = 0
                self._consecutive = max(self._consecutive + 1,
                                        self._trip_after)
                self._open_since = time.monotonic()

    def _probe_inconclusive(self) -> None:
        """A half-open probe that ended for REQUEST reasons (deadline
        budget) proved nothing about the drive: release the probe slot
        so the next caller can probe, without touching fault state —
        otherwise the flag wedges and the drive stays offline forever."""
        with self._mu:
            self._half_open_probe = False

    def _call(self, op: str, fn, args, kwargs):
        if tracing.ACTIVE:
            # Every storage op becomes one span (drive + op name) —
            # the per-drive attribution layer of the trace tree. The
            # span covers admit + pool wait + the op itself; the
            # engine-level span above it carries the queue-wait split.
            with tracing.span("storage", f"disk.{op}",
                              {"drive": str(self.endpoint
                                            or self.root or "")}):
                return self._call_inner(op, fn, args, kwargs)
        return self._call_inner(op, fn, args, kwargs)

    def _call_inner(self, op: str, fn, args, kwargs):
        # Deadline pre-check BEFORE _admit(): an already-exhausted
        # request must not consume the breaker's half-open probe slot.
        dl = deadline_mod.current()
        if dl is not None and dl.expired():
            raise DeadlineExceeded(
                f"request deadline exceeded before {op} on "
                f"{self.endpoint}")
        self._admit()
        base = self._bulk_timeout if op in _BULK_OPS else self._op_timeout
        # Clamp the op deadline to the REQUEST's remaining budget
        # (utils/deadline.py): a request with 200 ms left must not wait
        # a full op timeout on this drive. A single clamped expiry is
        # the request running out of time, not breaker fuel; only a
        # generous-window streak becomes suspicion (_clamped_expiry).
        timeout = base
        if dl is not None:
            timeout = min(base, dl.remaining())
        t0 = time.monotonic()
        tctx, tparent = tracing.capture() if tracing.ACTIVE else (None, 0)
        if dl is None and tctx is None:
            fut: Future = self._pool.submit(fn, *args, **kwargs)
        else:
            # Re-bind the budget (and the trace scope) inside the pool
            # worker so nested layers (remote drives -> grid calls)
            # keep consuming it / parenting under this op's span.
            def run(_dl=dl, _tc=tctx, _tp=tparent):
                with deadline_mod.bind(_dl), tracing.bind(_tc, _tp):
                    return fn(*args, **kwargs)
            fut = self._pool.submit(run)
        try:
            result = fut.result(timeout=timeout)
        except FutureTimeout:
            self._record(op, time.monotonic() - t0, failed=True)
            if timeout < base:
                # The REQUEST's budget expired first; one such expiry
                # proves nothing about drive health, but a streak of
                # generous-window ones does (see _clamped_expiry) —
                # otherwise a budget permanently shorter than the op
                # timeout would starve the breaker of evidence and a
                # dead drive could never fail fast.
                self._clamped_expiry(timeout)
                raise DeadlineExceeded(
                    f"request deadline exceeded during {op} on "
                    f"{self.endpoint}") from None
            self._fault()
            raise FaultyDisk(
                f"drive {self.endpoint}: {op} exceeded {timeout}s") from None
        except DeadlineExceeded:
            # Raised by a nested layer (e.g. a remote drive's grid
            # call): the request's problem, not this drive's.
            self._record(op, time.monotonic() - t0, failed=True)
            self._probe_inconclusive()
            raise
        except _DOMAIN_ERRORS:
            # The drive responded; the object/volume state is the news.
            self._record(op, time.monotonic() - t0, failed=False)
            self._ok()
            raise
        except Exception:
            self._record(op, time.monotonic() - t0, failed=True)
            self._fault()
            raise
        self._record(op, time.monotonic() - t0, failed=False)
        self._ok()
        return result

    _END = object()

    def _guarded_iter(self, name: str, attr, args, kwargs):
        """Deadline-bounded iteration of a generator op: creating the
        generator is instant, the I/O happens per next() — so every
        step runs through the breaker/deadline machinery."""
        it = iter(attr(*args, **kwargs))

        def step():
            try:
                return next(it)
            except StopIteration:
                return self._END

        while True:
            item = self._call(name, step, (), {})
            if item is self._END:
                return
            yield item

    def __getattr__(self, name: str):
        attr = getattr(self._disk, name)
        if not callable(attr):
            return attr
        cache = self.__dict__.setdefault("_bound_cache", {})
        hit = cache.get(name)
        if hit is not None:
            return hit

        if name in _GENERATOR_OPS:
            def bound(*args, **kwargs):
                return self._guarded_iter(name, attr, args, kwargs)
        else:
            def bound(*args, **kwargs):
                return self._call(name, attr, args, kwargs)
        cache[name] = bound
        return bound

    def close(self) -> None:
        self._pool.shutdown()


def wrap_disks(disks, **kwargs) -> list:
    """Health-wrap a drive list (OfflineDisk placeholders pass through —
    they already fail fast)."""
    out = []
    for d in disks:
        if d is None or type(d).__name__ == "OfflineDisk" \
                or isinstance(d, DiskHealthWrapper):
            out.append(d)
        else:
            out.append(DiskHealthWrapper(d, **kwargs))
    return out
