"""Programmable fault injection for storage drives.

The analogue of the reference's naughtyDisk test double
(cmd/naughty-disk_test.go:33): wraps any StorageAPI-shaped drive and
fails calls according to a programmed schedule, so quorum paths (write
quorum counting, degraded reads, heal classification, MRF hooks) can be
unit-tested against DETERMINISTIC failure sequences instead of killed
processes.

Schedules:
  * per-call-number: {3: OSError("boom")} fails the 3rd call (1-based,
    counted across all ops) and passes others through;
  * per-op: fail_ops={"create_file": OSError(...)} fails every call of
    that op;
  * default_err: if set, ANY call not matched above raises it (the
    reference's odd "default error" mode).
Counters are exposed for assertions; `calls` records (op, args) tuples.
"""

from __future__ import annotations

import threading
from typing import Optional


class NaughtyDisk:
    def __init__(self, disk, fail_calls: Optional[dict] = None,
                 fail_ops: Optional[dict] = None,
                 default_err: Optional[Exception] = None):
        self._disk = disk
        self.fail_calls = dict(fail_calls or {})
        self.fail_ops = dict(fail_ops or {})
        self.default_err = default_err
        self.call_count = 0
        self.calls: list = []
        self._mu = threading.Lock()

    @property
    def wrapped(self):
        return self._disk

    @property
    def endpoint(self):
        return getattr(self._disk, "endpoint", "naughty")

    @property
    def root(self):
        return getattr(self._disk, "root", None)

    def _maybe_fail(self, op: str, args) -> None:
        with self._mu:
            self.call_count += 1
            n = self.call_count
            self.calls.append((op, args))
            err = self.fail_calls.get(n)
        if err is not None:
            raise err
        err = self.fail_ops.get(op)
        if err is not None:
            raise err
        if self.default_err is not None:
            raise self.default_err

    def __getattr__(self, name: str):
        attr = getattr(self._disk, name)
        if not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            self._maybe_fail(name, args)
            return attr(*args, **kwargs)
        return wrapped
