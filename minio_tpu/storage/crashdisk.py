"""CrashDisk: a power-loss fault double (sibling of NaughtyDisk).

NaughtyDisk models a drive that ERRORS; CrashDisk models the node
LOSING POWER mid-write: every drive of the node stops at the same
instant (one shared CrashClock), the in-flight mutation is torn or
dropped according to what the syscall sequence had durably committed,
and every call after the cut fails with PowerCut — the process is gone.

The clock ticks once per durable MUTATION SUB-STEP, so a crash point
can land BETWEEN the halves of a composite commit (rename_data moves
the data dir, then writes xl.meta — the reference's RenameData,
cmd/xl-storage.go:2557; delete_version rewrites the journal, then
reclaims shard data). Sweeping crash_at over 1..N therefore walks
every interesting interleaving of a PUT/multipart/delete/heal commit
fan-out, which is exactly what the crash-point matrix tests do.

Tear modes (what the platter holds for the interrupted write):
  * "drop" — buffered bytes never hit the platter: the mutation has
    no effect (the page cache died with the power);
  * "tear" — a prefix of the in-flight write landed: torn shard files
    appear in staging, torn journal writes appear as tmp files (the
    protocol stages both; a torn file never sits at a commit
    destination), and an interrupted rename_data leaves its data dir
    moved in with no journal claim;
  * "lose_entry" — a non-journaling filesystem without directory
    fsync: in addition to dropping the in-flight write, the LAST
    completed-but-unsynced rename on every drive is rolled back (its
    directory entry was still in the cache). MTPU_FS_OSYNC exists
    precisely because this mode can surface the OLD version of a
    quorum-acknowledged write — the matrix asserts old-or-new here,
    never durability.
"""

from __future__ import annotations

import os
import shutil
import sys
import threading
from typing import Optional

from minio_tpu.storage.local import SYS_VOL, TMP_DIR, PowerFault

TEAR_MODES = ("drop", "tear", "lose_entry")


class PowerCut(PowerFault):
    """The node lost power: this and every later call cannot happen.

    Subclasses local.PowerFault so commit_group propagates it
    WHOLESALE instead of recording it as one member's error."""


class CrashClock:
    """Shared mutation counter across all of one node's CrashDisks.

    crash_at: the 1-based mutation sub-step at which power dies
    (0 = never). Registered disks get their lose_entry rollback applied
    the moment the clock fires, from whichever thread fired it.
    """

    def __init__(self, crash_at: int = 0):
        self.crash_at = crash_at
        self.count = 0
        self.fired = False
        # Which mutator the cut landed in (the caller's function name,
        # captured at fire time) — sweep assertion messages name the
        # sub-step instead of just its ordinal.
        self.fired_op = ""
        self._mu = threading.Lock()
        self._disks: list = []

    def register(self, disk: "CrashDisk") -> None:
        with self._mu:
            self._disks.append(disk)

    def dead(self) -> bool:
        with self._mu:
            return self.fired

    def tick(self) -> bool:
        """Advance one mutation sub-step. True = the power dies ON this
        sub-step (the caller applies its partial effect, then raises).
        Raises PowerCut when the node is ALREADY dead — an op that was
        mid-flight when the power died cannot perform its remaining
        sub-steps."""
        with self._mu:
            if self.fired:
                raise PowerCut("node lost power")
            self.count += 1
            if self.crash_at and self.count == self.crash_at:
                self.fired = True
                try:
                    self.fired_op = sys._getframe(1).f_code.co_name
                except Exception:  # noqa: BLE001 - diagnostics only
                    self.fired_op = ""
                disks = list(self._disks)
            else:
                return False
        for d in disks:
            d._on_power_cut()
        return True


# Ops that mutate durable state, with their sub-step count. Everything
# else passes through while the node is alive.
_MUTATORS = {
    "create_file": 1, "write_all": 1, "write_metadata": 1,
    "update_metadata": 1, "write_format": 1, "rename_file": 1,
    "make_vol": 1, "make_vol_if_missing": 1, "delete_vol": 1,
    "delete": 1,
    "rename_data": 2,       # data-dir move | journal commit
    "delete_version": 2,    # journal rewrite | data-dir reclaim
}


class CrashDisk:
    """Wraps a LocalStorage with the power-cut model above. The double
    knows LocalStorage's on-disk layout (it must, to fabricate the
    partial states a real cut leaves behind)."""

    def __init__(self, disk, clock: CrashClock, mode: str = "drop"):
        if mode not in TEAR_MODES:
            raise ValueError(f"unknown tear mode {mode!r}")
        self._disk = disk
        self._clock = clock
        self.mode = mode
        self._mu = threading.Lock()
        # (dest_path, prior_bytes_or_None) of the most recent atomic
        # rename-commit — the un-fsynced directory entry lose_entry
        # rolls back when the power dies.
        self._last_commit: Optional[tuple] = None
        # Group-commit renames whose CONTENT was never fdatasync'd
        # (commit_group writes destinations tmp+rename with the WAL as
        # the durability point): (dest, new_blob, prior). At a power
        # cut, drop/tear leave the rename durable with TORN content
        # (the page cache died); lose_entry loses the rename's dir
        # entry instead (dest reverts to prior). Entries retire when a
        # checkpoint's os.sync completes.
        self._unsynced: list = []
        # WAL files whose gcommit/ dir entry was never synced: lost
        # under lose_entry (the documented MTPU_FS_OSYNC exception —
        # FS_OSYNC dir-syncs gcommit/ and clears this).
        self._unsynced_wals: list = []
        # The background checkpoint coordinator must never touch this
        # drive's WAL: the power-cut double owns durability timing —
        # checkpoints happen only through the hook-ticked
        # gc_checkpoint() above.
        if hasattr(disk, "_gc_auto"):
            disk._gc_auto = False
        clock.register(self)

    @property
    def wrapped(self):
        return self._disk

    @property
    def endpoint(self):
        return getattr(self._disk, "endpoint", "crash")

    @property
    def root(self):
        return getattr(self._disk, "root", None)

    # -- power-cut effects ----------------------------------------------

    def _check_alive(self) -> None:
        if self._clock.dead():
            raise PowerCut(f"drive {self.endpoint}: node lost power")

    def _on_power_cut(self) -> None:
        """Called once when the clock fires (any disk, any thread)."""
        with self._mu:
            unsynced, self._unsynced = self._unsynced, []
            uwals, self._unsynced_wals = self._unsynced_wals, []
            last, self._last_commit = self._last_commit, None
        # Group-commit destinations with un-fsynced content: the power
        # cut tears them (drop/tear — the rename's entry is journaled,
        # the cached pages are not) or voids the rename outright
        # (lose_entry). replay_wals repairs the former from the WAL.
        for dest, blob, prior in unsynced:
            try:
                if self.mode == "lose_entry":
                    if prior is None:
                        os.remove(dest)
                    else:
                        with open(dest, "wb") as f:
                            f.write(prior)
                else:
                    with open(dest, "wb") as f:
                        f.write(blob[:len(blob) // 2])
            except OSError:
                pass
        if self.mode != "lose_entry":
            return
        for wal in uwals:
            try:
                os.remove(wal)
            except OSError:
                pass
        if last is None:
            return
        dest, prior = last
        try:
            if prior is None:
                if os.path.isdir(dest):
                    shutil.rmtree(dest, ignore_errors=True)
                else:
                    os.remove(dest)
            else:
                with open(dest, "wb") as f:
                    f.write(prior)
        except OSError:
            pass

    def _note_commit_file(self, dest: str, prior: Optional[bytes]) -> None:
        """Record a completed journal rename-commit (dest + the bytes
        it replaced, None = fresh file) so lose_entry can void the
        un-fsynced directory entry when the power dies."""
        if self.mode != "lose_entry":
            return
        with self._mu:
            self._last_commit = (dest, prior)

    def _tear_tmp(self, payload: bytes) -> None:
        """Leave a torn tmp file behind (mode=tear): the half-written
        staging file of an interrupted atomic write."""
        if self.mode != "tear" or self.root is None:
            return
        import uuid
        tmp = os.path.join(self.root, SYS_VOL, TMP_DIR,
                           f"torn-{uuid.uuid4()}")
        try:
            os.makedirs(os.path.dirname(tmp), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(payload[:max(0, len(payload) // 2)])
        except OSError:
            pass

    # -- mutators --------------------------------------------------------

    def _meta_prior(self, volume: str, path: str) -> Optional[bytes]:
        """Current journal bytes (None = absent) for lose_entry."""
        if self.mode != "lose_entry" or self.root is None:
            return None
        try:
            with open(os.path.join(self.root, volume, path,
                                   "xl.meta"), "rb") as f:
                return f.read()
        except OSError:
            return None

    def create_file(self, volume, path, data):
        self._check_alive()
        if self._clock.tick():
            if self.mode == "tear":
                # A prefix of the shard stream made it to the platter.
                blob = data if isinstance(data, (bytes, bytearray)) \
                    else b"".join(bytes(c) for c in data)
                dest = self._disk._obj_dir(volume, path)
                try:
                    os.makedirs(os.path.dirname(dest), exist_ok=True)
                    with open(dest, "wb") as f:
                        f.write(blob[:max(0, len(blob) - 1) // 2])
                except OSError:
                    pass
            raise PowerCut(f"{self.endpoint}: power cut in create_file")
        return self._disk.create_file(volume, path, data)

    def _simple_atomic(self, op, volume, path, payload, *args, **kwargs):
        self._check_alive()
        if self._clock.tick():
            self._tear_tmp(payload)
            raise PowerCut(f"{self.endpoint}: power cut in {op}")
        prior = self._meta_prior(volume, path) \
            if op in ("write_metadata", "update_metadata") else None
        result = getattr(self._disk, op)(volume, path, *args, **kwargs)
        if op in ("write_metadata", "update_metadata"):
            self._note_commit_file(
                os.path.join(self.root, volume, path, "xl.meta"), prior)
        return result

    def write_all(self, volume, path, data):
        return self._simple_atomic("write_all", volume, path, data, data)

    def write_metadata(self, volume, path, fi):
        return self._simple_atomic("write_metadata", volume, path, b"",
                                   fi)

    def update_metadata(self, volume, path, fi):
        return self._simple_atomic("update_metadata", volume, path, b"",
                                   fi)

    def rename_data(self, src_volume, src_path, fi, dst_volume, dst_path):
        self._check_alive()
        d = self._disk
        dst_dir = d._obj_dir(dst_volume, dst_path)
        # Sub-step 1: the data-dir move. In tear mode the rename's
        # entry is taken as durable (journaled), so an interrupted
        # commit leaves the moved-in data dir with no journal claim —
        # the dangling state recovery_sweep must undo.
        if self._clock.tick():
            if self.mode == "tear" and fi.data_dir:
                try:
                    src_data = os.path.join(
                        d._obj_dir(src_volume, src_path), fi.data_dir)
                    os.makedirs(dst_dir, exist_ok=True)
                    os.replace(src_data,
                               os.path.join(dst_dir, fi.data_dir))
                except OSError:
                    pass
            raise PowerCut(
                f"{self.endpoint}: power cut moving data dir")
        # Sub-step 2: the journal commit (the commit point).
        if self._clock.tick():
            if fi.data_dir:
                try:
                    src_data = os.path.join(
                        d._obj_dir(src_volume, src_path), fi.data_dir)
                    os.makedirs(dst_dir, exist_ok=True)
                    os.replace(src_data,
                               os.path.join(dst_dir, fi.data_dir))
                except OSError:
                    pass
            self._tear_tmp(b"x" * 256)
            raise PowerCut(
                f"{self.endpoint}: power cut committing journal")
        prior = self._meta_prior(dst_volume, dst_path)
        result = d.rename_data(src_volume, src_path, fi, dst_volume,
                               dst_path)
        self._note_commit_file(os.path.join(dst_dir, "xl.meta"), prior)
        return result

    def delete_version(self, volume, path, version_id="",
                       force_del_marker=False):
        self._check_alive()
        # Sub-step 1: the journal rewrite.
        if self._clock.tick():
            raise PowerCut(
                f"{self.endpoint}: power cut before journal rewrite")
        # Sub-step 2: shard-data reclaim. A cut here = journal already
        # rewritten (the delete IS committed) but the version's data
        # dir survives as garbage — the dangling state the recovery
        # sweep removes.
        if self._clock.tick():
            self._partial_delete_version(volume, path, version_id)
            raise PowerCut(
                f"{self.endpoint}: power cut reclaiming data dir")
        return self._disk.delete_version(volume, path, version_id,
                                         force_del_marker)

    def _partial_delete_version(self, volume, path, version_id) -> None:
        """Journal rewritten, data dir left behind."""
        from minio_tpu.storage import meta as metafmt
        d = self._disk
        try:
            with d._path_lock(volume, path):
                xl = d._read_meta(volume, path)
                xl.delete_version(version_id)
                meta_path = d._meta_path(volume, path)
                if not xl.versions:
                    os.remove(meta_path)
                else:
                    d._atomic_write(meta_path, xl.dump())
        except (OSError, metafmt.MetaError, metafmt.FileNotFoundErr,
                metafmt.VersionNotFoundErr):
            pass

    # -- group commit (storage/group_commit lanes) -----------------------

    def commit_group(self, ops, _info=None):
        """The batched commit with a crash point at EVERY durable
        sub-step boundary: each rename_data member's data-dir move,
        the multi-object WAL write, each destination journal rename,
        and the checkpoint's sync — the composite sub-steps the
        group-commit crash matrix sweeps."""
        self._check_alive()
        return self._disk.commit_group(ops, _info=_info,
                                       _hook=_GCHook(self))

    def gc_checkpoint(self):
        self._check_alive()
        return self._disk.gc_checkpoint(_hook=_GCHook(self))

    def __getattr__(self, name: str):
        attr = getattr(self._disk, name)
        if not callable(attr):
            return attr

        if name in _MUTATORS:
            def mutate(*args, **kwargs):
                self._check_alive()
                if self._clock.tick():
                    raise PowerCut(
                        f"{self.endpoint}: power cut in {name}")
                return attr(*args, **kwargs)
            return mutate

        def passthrough(*args, **kwargs):
            self._check_alive()
            return attr(*args, **kwargs)
        return passthrough


class _GCHook:
    """commit_group's crash-injection seam, bound to one CrashDisk.

    LocalStorage.commit_group calls these at every durable sub-step
    boundary; each tick can fire the shared clock, fabricate the
    partial on-disk state a real cut would leave at that instant, and
    raise PowerCut. note_* calls record completed-but-not-yet-durable
    effects so a LATER cut (any op, any disk) tears them retroactively
    in _on_power_cut — the page cache dies with the node, not with the
    op that filled it."""

    __slots__ = ("cd",)

    def __init__(self, cd: CrashDisk):
        self.cd = cd

    def step_move(self, op) -> None:
        cd = self.cd
        if cd._clock.tick():
            if cd.mode == "tear" and op.fi.data_dir:
                d = cd._disk
                try:
                    src = os.path.join(
                        d._obj_dir(op.src_volume, op.src_path),
                        op.fi.data_dir)
                    dst_dir = d._obj_dir(op.volume, op.path)
                    os.makedirs(dst_dir, exist_ok=True)
                    os.replace(src, os.path.join(dst_dir, op.fi.data_dir))
                except OSError:
                    pass
            raise PowerCut(f"{cd.endpoint}: power cut moving data dir "
                           "(group commit)")

    def step_wal(self, path: str, frame: bytes) -> None:
        cd = self.cd
        if cd._clock.tick():
            if cd.mode == "tear":
                # Torn multi-object WAL frame: a prefix of the append
                # landed. The frame crc makes it self-evident at
                # replay; it protects nobody.
                try:
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    with open(path, "ab") as f:
                        f.write(frame[:max(0, len(frame) - 1) // 2])
                except OSError:
                    pass
            raise PowerCut(f"{cd.endpoint}: power cut writing group WAL")

    def note_wal(self, path: str, synced_dir: bool) -> None:
        cd = self.cd
        if cd.mode == "lose_entry" and not synced_dir:
            with cd._mu:
                cd._unsynced_wals.append(path)

    def meta_prior(self, volume: str, path: str):
        return self.cd._meta_prior(volume, path)

    def step_rename(self, dest: str, blob: bytes) -> None:
        cd = self.cd
        if cd._clock.tick():
            # Power dies BEFORE this rename: this destination keeps its
            # old journal; earlier renames of the same batch are torn
            # by _on_power_cut (their content was never synced).
            raise PowerCut(f"{cd.endpoint}: power cut in batched "
                           "rename sequence")

    def note_rename(self, dest: str, blob: bytes, prior) -> None:
        cd = self.cd
        with cd._mu:
            cd._unsynced.append((dest, bytes(blob),
                                 None if prior is None else bytes(prior)))

    def step_sync(self) -> None:
        cd = self.cd
        if cd._clock.tick():
            # Cut during the checkpoint: the sync never happened —
            # unsynced destinations tear, live WALs survive for replay.
            raise PowerCut(f"{cd.endpoint}: power cut in WAL checkpoint")
        with cd._mu:
            cd._unsynced.clear()
            cd._unsynced_wals.clear()
