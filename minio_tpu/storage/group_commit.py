"""Group-committed small-object write plane: per-drive commit lanes.

The metadata twin of ops/batcher.py (ROADMAP item 4): every inline PUT
commits one version into xl.meta on EVERY drive — a full per-drive
journal read-modify-write plus a tmp-write + fdatasync + rename under a
per-path lock. N concurrent small objects = N durable commits per
drive, so at KV scale the commit machinery, not the codec, is the wall.
This module coalesces them: concurrent `write_metadata`/`rename_data`
calls targeting the same drive accumulate into one deadline-bounded
batch (adaptive window like the stripe batcher — stretches while bursts
keep filling batches, shrinks when traffic is sparse, closes early at
the earliest member deadline minus slack, deadline-exhausted members
culled alone) and commit as ONE journal pass per drive
(storage/local.LocalStorage.commit_group):

  1. staged data dirs move in (rename_data members);
  2. one journal read-modify-write per DISTINCT object — same-object
     members merge in arrival order, so a hot-key overwrite storm is
     one xl.meta rewrite, and byte-identical re-adds (heal/MRF storms)
     short-circuit entirely;
  3. ONE write-ahead frame appended to the drive's WAL
     (`<drive>/.mtpu.sys/gcommit/wal-p<pid>.log`, held open across
     batches) holding every merged journal, made durable with ONE
     fdatasync — the batch's durability point, amortized across all
     members, and the only filesystem-journal transaction the batch
     forces (no per-batch file create/unlink);
  4. each journal lands via plain tmp + rename (no per-file fdatasync:
     the WAL already holds the bytes durably; a destination torn by a
     power cut is repaired from the WAL at mount time — replay_wals);
  5. one `_fsync_dir` pass over the distinct parent dirs under
     MTPU_FS_OSYNC.

Each member's ack is deferred until the batch's commit point lands, so
per-object durability semantics are unchanged: an acknowledged write is
either in its destination journal or in a durable WAL that mount-time
recovery replays (storage/local.recovery_sweep runs replay_wals FIRST,
before the dangling-data-dir scan — the WAL's journal claims must be
reinstated before orphan collection looks). Retired WAL files are
garbage-collected lazily: every MTPU_GROUP_COMMIT_CKPT_S seconds one
os.sync() makes the renamed destinations durable and the retired WALs
unlink; replaying a WAL whose destinations already committed is
idempotent (newer journals win by mtime). The sync runs on ONE
process-wide coordinator thread, never on the commit path.

A member's failure demotes that member — and only it — to the solo
path (plain write_metadata/rename_data); batch-mates are unaffected.
Commit dispatches ride the drive's io/engine submission queue, so the
engine's wait-vs-service split attributes coalesced commits exactly
like solo ops, and ONE `commit` span per batch is fanned into every
member's trace tree (utils/tracing.record_into, like the kernel span).

Environment:
  MTPU_GROUP_COMMIT          on|off (default on): the lane entirely.
  MTPU_GROUP_COMMIT_WAIT_MS  max accumulation window (default 30.0).
  MTPU_GROUP_COMMIT_MAX      max members per drive batch (default 128).
  MTPU_GROUP_COMMIT_CKPT_S   seconds between WAL checkpoints (def 2.0).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import uuid as uuid_mod
import weakref
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional

import msgpack

from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import DeadlineExceeded
from minio_tpu.utils.env import env_float, env_int
from minio_tpu.utils.latency import Histogram

GC_DIR = "gcommit"
GC_MAGIC = b"GCW1"

# A member must dispatch at least this long before its deadline: the
# commit (journal merges + WAL fsync + renames) must fit in what
# remains of the request budget.
_DEADLINE_SLACK_S = 0.005
_MIN_WAIT_S = 0.00025


def enabled() -> bool:
    return os.environ.get("MTPU_GROUP_COMMIT", "on").lower() \
        not in ("0", "off", "false")


def base_wait_s() -> float:
    """Max accumulation window. Generous by design: the early-close
    rule (pending >= in-flight requests) dispatches long before this
    whenever the submitters can keep up, so light load never waits it
    out — the cap binds only at saturation, where arrivals are slower
    than the window and queueing latency dwarfs it anyway (fill, and
    with it the per-request share of batch overhead, scales with the
    cap there)."""
    return env_float("MTPU_GROUP_COMMIT_WAIT_MS", 30.0) / 1000.0


def max_members() -> int:
    return env_int("MTPU_GROUP_COMMIT_MAX", 128)


def ckpt_interval_s() -> float:
    return env_float("MTPU_GROUP_COMMIT_CKPT_S", 2.0)


# ---------------------------------------------------------------------------
# WAL retirement: the background checkpoint coordinator
# ---------------------------------------------------------------------------
# A committed batch's WAL may only unlink once its renamed destination
# journals are durable. Syncing on the commit path would put a global
# flush in the hot loop, so retirement is deferred: drives queue their
# retired WALs and ONE process-wide coordinator makes everything
# durable with a single os.sync per interval (the sync is global, so
# one call covers every drive), then unlinks the batch. A WAL that
# outlives its process (SIGKILL before the interval) is replayed
# idempotently at the next boot.

_co_mu = threading.Lock()
_co_disks: "weakref.WeakSet" = weakref.WeakSet()
_co_thread: Optional[threading.Thread] = None
checkpoints_total = 0
wals_retired_total = 0


def schedule_checkpoint(disk) -> None:
    """Register `disk` (a LocalStorage with retired WALs pending) with
    the coordinator; spawns/respawns the daemon on demand."""
    global _co_thread
    with _co_mu:
        _co_disks.add(disk)
        if _co_thread is None or not _co_thread.is_alive():
            _co_thread = threading.Thread(
                target=_co_loop, daemon=True, name="gc-checkpoint")
            _co_thread.start()


def _co_loop() -> None:
    global _co_thread, checkpoints_total, wals_retired_total
    idle = 0
    while True:
        time.sleep(ckpt_interval_s())
        with _co_mu:
            disks = list(_co_disks)
        dirty = [d for d in disks
                 if getattr(d, "gc_pending", lambda: 0)()]
        if not dirty:
            idle += 1
            if idle >= 3:
                with _co_mu:
                    # Exit only when nothing arrived since the last
                    # scan — appends always re-poke via
                    # schedule_checkpoint, which sees the dead handle
                    # and respawns.
                    if not any(getattr(d, "gc_pending", lambda: 0)()
                               for d in _co_disks):
                        _co_thread = None
                        return
                idle = 0
            continue
        idle = 0
        # Capture each drive's frame count BEFORE the sync: frames
        # appended after it were not made durable by it, and truncating
        # them would erase an acked batch's durability point — the
        # guarded truncate skips any drive that moved and retires it
        # next round instead.
        pre = {}
        for d in dirty:
            try:
                pre[id(d)] = d.gc_pending()
            except Exception:  # noqa: BLE001 - drive gone mid-ckpt
                pre[id(d)] = 0
        try:
            # ONE global sync covers every drive's renamed journal
            # destinations; only then may their WAL frames drop.
            os.sync()
        except OSError:
            pass
        frames = 0
        for d in dirty:
            try:
                frames += d.gc_truncate_wal(expect=pre.get(id(d)))
            except Exception:  # noqa: BLE001 - drive gone mid-ckpt
                pass
        with _co_mu:
            checkpoints_total += 1
            wals_retired_total += frames


@dataclass
class GroupOp:
    """One member of a per-drive commit batch."""
    kind: str                  # "wm" (write_metadata) | "rd" (rename_data)
    volume: str
    path: str
    fi: object                 # storage.meta.FileInfo
    src_volume: str = ""       # rename_data staging source
    src_path: str = ""

    @classmethod
    def write_meta(cls, volume, path, fi) -> "GroupOp":
        return cls("wm", volume, path, fi)

    @classmethod
    def rename(cls, src_volume, src_path, fi, volume, path) -> "GroupOp":
        return cls("rd", volume, path, fi,
                   src_volume=src_volume, src_path=src_path)


# ---------------------------------------------------------------------------
# WAL encode / decode / replay
# ---------------------------------------------------------------------------
# One append-mode WAL file per drive per process
# (`gcommit/wal-p<pid>.log`, held open across batches): each batch
# appends ONE framed record and fdatasyncs it — no file create/unlink
# per batch, so the filesystem's metadata journal sees one data flush
# per batch instead of three metadata transactions (on ext4, creates
# and unlinks serialize behind exactly the journal commits the
# fdatasyncs force; the append design is what lets batch commits and
# journal renames flow concurrently). Checkpoints truncate the file in
# place. Frame layout:
#
#     GC_MAGIC | crc32(body) u32 | body = t_ns u64 | len u32 | payload
#
# where payload is msgpack [(volume, path, journal_blob), ...] and
# t_ns is the frame's creation time — every destination journal of the
# batch is renamed in AFTER t_ns, which is what replay's newer-wins
# mtime comparison relies on. The crc makes a torn tail frame (power
# cut mid-append) self-evident: it is discarded, and it protected
# nobody — no member of that batch was ever acked.

_FRAME_HEAD = struct.Struct("<I")       # crc32 over body
_FRAME_BODY_HEAD = struct.Struct("<QI")  # t_ns, payload length


def wal_file_path(root: str) -> str:
    from minio_tpu.storage.local import SYS_VOL
    return os.path.join(root, SYS_VOL, GC_DIR,
                        f"wal-p{os.getpid()}.log")


def encode_frame(recs: list[tuple[str, str, bytes]],
                 t_ns: Optional[int] = None) -> bytes:
    payload = msgpack.packb([(v, p, bytes(b)) for v, p, b in recs],
                            use_bin_type=True)
    body = _FRAME_BODY_HEAD.pack(
        time.time_ns() if t_ns is None else t_ns, len(payload)) + payload
    return GC_MAGIC + _FRAME_HEAD.pack(zlib.crc32(body)) + body


def iter_frames(blob: bytes):
    """Yield (t_ns, recs) for every intact frame; stops at the first
    torn/alien bytes (everything after a torn frame is unreachable —
    appends are strictly ordered). Returns the count of discarded
    tails (0 or 1) via StopIteration value; callers use the generator
    plainly and treat early exhaustion as the torn signal."""
    off = 0
    n = len(blob)
    while off + 20 <= n:   # full header: magic(4)+crc(4)+t_ns(8)+len(4)
        if blob[off:off + 4] != GC_MAGIC:
            return 1
        (crc,) = _FRAME_HEAD.unpack_from(blob, off + 4)
        t_ns, plen = _FRAME_BODY_HEAD.unpack_from(blob, off + 8)
        end = off + 20 + plen
        if end > n:
            return 1
        body = blob[off + 8:end]
        if zlib.crc32(body) != crc:
            return 1
        try:
            recs = msgpack.unpackb(body[12:], raw=False)
        except Exception:  # noqa: BLE001 - decodes like a torn frame
            return 1
        yield t_ns, [(v, p, b) for v, p, b in recs]
        off = end
    return 1 if off < n else 0


def _wal_improves(dest_blob: bytes, jblob: bytes) -> bool:
    """True when the WAL journal holds a version the destination
    journal lacks, or holds at an older mod time — i.e. installing the
    frame adds committed state instead of rolling newer state back.
    Unparsable inputs answer True (the torn-destination repair
    case)."""
    from minio_tpu.storage.meta import XLMeta
    try:
        dest = XLMeta.load(dest_blob)
        wal = XLMeta.load(jblob)
    except Exception:  # noqa: BLE001 - torn either side: repair
        return True
    have = {v.get("vid"): v.get("mt", 0) for v in dest.versions}
    return any(have.get(v.get("vid"), -1) < v.get("mt", 0)
               for v in wal.versions)


def replay_wals(disk) -> dict:
    """Mount-time WAL replay: repair/complete group commits a power
    cut interrupted. Every intact frame across the drive's WAL files
    is collected, sorted by frame time, and each recorded journal is
    installed — with a REAL fdatasync this time — iff its destination
    is missing, unreadable (torn by the cut: the rename landed but the
    un-synced content did not), or strictly older than the frame (the
    rename itself never landed). A destination newer than the frame is
    a later committed write and is left alone; a destination whose
    whole OBJECT DIR is gone is a post-batch delete and is NOT
    resurrected. Torn tail frames are discarded: they were never any
    member's durability point. WAL files are removed afterwards —
    replaying an already-committed batch is idempotent. Returns
    {"replayed", "repaired", "discarded"}."""
    from minio_tpu.storage.local import META_FILE, SYS_VOL
    from minio_tpu.storage.meta import MetaError, XLMeta
    out = {"replayed": 0, "repaired": 0, "discarded": 0}
    root = getattr(disk, "root", None) or \
        (disk if isinstance(disk, str) else None)
    if root is None:
        return out
    gdir = os.path.join(root, SYS_VOL, GC_DIR)
    try:
        names = sorted(os.listdir(gdir))
    except (FileNotFoundError, NotADirectoryError):
        return out
    entries: list[tuple[int, str, str, bytes]] = []
    for name in names:
        full = os.path.join(gdir, name)
        if not name.startswith("wal-"):
            # Stray replay tmp from an interrupted recovery: remove.
            try:
                os.remove(full)
            except OSError:
                pass
            continue
        try:
            with open(full, "rb") as f:
                blob = f.read()
        except OSError:
            continue
        it = iter_frames(blob)
        while True:
            try:
                t_ns, recs = next(it)
            except StopIteration as stop:
                out["discarded"] += stop.value or 0
                break
            out["replayed"] += 1
            for vol, path, jblob in recs:
                entries.append((t_ns, vol, path, jblob))
    # Frame-time order across files: pre-forked sibling workers append
    # to per-pid files, and for one object the NEWEST frame must win.
    entries.sort(key=lambda e: e[0])
    for t_ns, vol, path, jblob in entries:
        obj_dir = os.path.join(root, vol, path)
        dest = os.path.join(obj_dir, META_FILE)
        if not os.path.isdir(obj_dir):
            # Whole object dir gone: a committed post-batch delete
            # pruned it (or, under lose_entry semantics, a fresh
            # object's dir entry was lost — the documented
            # MTPU_FS_OSYNC durability exception). Never resurrect.
            continue
        install = False
        try:
            st = os.stat(dest)
            with open(dest, "rb") as f:
                dest_blob = f.read()
            if st.st_mtime_ns < t_ns:
                # Looks pre-batch (rename lost) — but mtime alone can
                # lie on coarse-granularity filesystems or across a
                # clock step, and blindly installing would roll a
                # NEWER committed overwrite back to the frame's
                # journal. Install only when the frame really carries
                # a version the destination lacks (or holds older).
                install = _wal_improves(dest_blob, jblob)
            else:
                XLMeta.load(dest_blob)
        except FileNotFoundError:
            install = True              # rename never landed
        except Exception:  # noqa: BLE001 - unreadable == torn: repair
            install = True
        if install:
            tmp = os.path.join(gdir, f"replay-{uuid_mod.uuid4().hex}")
            try:
                with open(tmp, "wb") as f:
                    f.write(jblob)
                    f.flush()
                    os.fdatasync(f.fileno())
                os.replace(tmp, dest)
                out["repaired"] += 1
            except OSError:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    for name in names:
        if name.startswith("wal-"):
            try:
                os.remove(os.path.join(gdir, name))
            except OSError:
                pass
    return out


# ---------------------------------------------------------------------------
# the coalescer
# ---------------------------------------------------------------------------

class _Latch:
    """One countdown shared by a request's members: ONE wait and ONE
    wake per request instead of one per drive (the same trick
    ErasureSet._fanout pulls — future-per-op handoff cost is real at
    12+ drives)."""

    __slots__ = ("event", "mu", "n")

    def __init__(self, n: int):
        self.n = n
        self.mu = threading.Lock()
        self.event = threading.Event()
        if n <= 0:
            # Nothing to wait for (e.g. every drive slot was None
            # because staging failed everywhere): an unset event here
            # would park the caller forever inside the namespace lock.
            self.event.set()

    def dec(self) -> None:
        with self.mu:
            self.n -= 1
            if self.n <= 0:
                self.event.set()


class _Member:
    __slots__ = ("op", "latch", "exc", "done", "expires_at", "tctx",
                 "tparent", "t_enq")

    def __init__(self, op: GroupOp, dl, latch: _Latch):
        self.op = op
        self.latch = latch
        self.done = False
        self.exc: Optional[BaseException] = None
        self.expires_at = dl.expires_at if dl is not None else None
        self.tctx, self.tparent = tracing.capture() if tracing.ACTIVE \
            else (None, 0)
        self.t_enq = time.perf_counter()


@dataclass
class _Lane:
    idx: int
    name: str
    pending: list = field(default_factory=list)
    deadline: float = 0.0          # current window's dispatch-by time
    cur_wait: float = 0.0
    min_expiry: Optional[float] = None   # earliest member deadline

    def bound(self) -> float:
        """When this window must close: the adaptive deadline, pulled
        in to the earliest member deadline minus commit slack."""
        if self.min_expiry is None:
            return self.deadline
        return min(self.deadline, self.min_expiry - _DEADLINE_SLACK_S)


# Live coalescers, for fleet-wide metrics (s3/metrics.py renders
# minio_tpu_group_commit_* from aggregate_stats()).
_REGISTRY: "weakref.WeakSet[GroupCommit]" = weakref.WeakSet()


def _zero_stats() -> dict:
    return {
        "batches": 0, "members": 0, "solo_bypass": 0,
        "objects": 0, "merged_members": 0, "noop_skips": 0,
        "fsyncs_saved": 0, "deadline_culls": 0, "solo_demotions": 0,
        "size_buckets": {}, "wait_hist": None, "fill_mean": 0.0,
    }


def aggregate_stats() -> dict:
    out = _zero_stats()
    hists = []
    for gc in list(_REGISTRY):
        st = gc.stats()
        for key in ("batches", "members", "solo_bypass", "objects",
                    "merged_members", "noop_skips", "fsyncs_saved",
                    "deadline_culls", "solo_demotions"):
            out[key] += st[key]
        for b, v in st["size_buckets"].items():
            out["size_buckets"][b] = out["size_buckets"].get(b, 0) + v
        hists.append(st["wait_hist"])
    out["wait_hist"] = Histogram.merge(hists) if hists \
        else Histogram().state()
    out["fill_mean"] = (out["members"] / out["batches"]) \
        if out["batches"] else 0.0
    out["checkpoints"] = checkpoints_total
    out["wals_retired"] = wals_retired_total
    return out


def merge_stats(states: list) -> dict:
    """Fleet view: sum per-worker aggregate_stats() snapshots (each
    pre-forked worker runs its OWN lanes over the shared drives, and a
    scrape lands on an arbitrary worker — same merge the engine's
    per-drive rows get)."""
    out = _zero_stats()
    out["checkpoints"] = 0
    out["wals_retired"] = 0
    hists = []
    for st in states:
        if not isinstance(st, dict):
            continue
        for key in ("batches", "members", "solo_bypass", "objects",
                    "merged_members", "noop_skips", "fsyncs_saved",
                    "deadline_culls", "solo_demotions",
                    "checkpoints", "wals_retired"):
            out[key] += st.get(key, 0)
        for b, v in (st.get("size_buckets") or {}).items():
            b = int(b)
            out["size_buckets"][b] = out["size_buckets"].get(b, 0) + v
        if st.get("wait_hist"):
            hists.append(st["wait_hist"])
    out["wait_hist"] = Histogram.merge(hists) if hists \
        else Histogram().state()
    out["fill_mean"] = (out["members"] / out["batches"]) \
        if out["batches"] else 0.0
    return out


def _size_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class GroupCommit:
    """Per-drive group-commit lanes of one erasure set.

    `disks` are the set's (health-wrapped) drives; `io_engine` its
    per-drive submission queues — batch commits are dispatched through
    them so the engine's queue-wait/service split covers coalesced
    commits. `bump` (set by the erasure layer to metacache.bump) fires
    ONE coalesced invalidation per batch per distinct bucket, BEFORE
    any member is acked — the same before-return semantics per-request
    bumps had, one funnel call per batch instead of per mutation."""

    def __init__(self, disks, io_engine, name: str = ""):
        self._disks = list(disks)
        self._io = io_engine
        self.name = name
        self.bump: Optional[Callable[[str], None]] = None
        base = base_wait_s()
        self._max_wait = base
        self._max_members = max_members()
        self._lanes = [
            _Lane(i, str(getattr(d, "endpoint", "") or i),
                  cur_wait=base / 4)
            for i, d in enumerate(self._disks)]
        self._mu = threading.Condition()
        self._dispatcher: Optional[threading.Thread] = None
        self._inflight = 0
        self._closed = False
        self._stat_mu = threading.Lock()
        self._batches = 0
        self._members = 0
        self._solo_bypass = 0
        self._objects = 0
        self._merged_members = 0
        self._noop_skips = 0
        self._fsyncs_saved = 0
        self._deadline_culls = 0
        self._solo_demotions = 0
        self._size_buckets: dict[int, int] = {}
        self._wait_hist = Histogram()
        _REGISTRY.add(self)

    # -- submission -----------------------------------------------------

    def tracking(self):
        """Context manager marking one group-eligible request in its
        commit section — the concurrency signal worth_batching reads
        (mirror of the stripe batcher's inflight bookkeeping)."""
        gc = self

        class _Track:
            def __enter__(self):
                with gc._mu:
                    gc._inflight += 1
                return gc

            def __exit__(self, *exc):
                with gc._mu:
                    gc._inflight -= 1
                return False

        return _Track()

    def worth_batching(self) -> bool:
        """True when coalescing has company RIGHT NOW: another
        group-eligible request is in its commit section, or members are
        already pending. A lone request (the caller counts as 1) takes
        the solo fan-out and never waits the window."""
        if self._inflight > 1:
            return True
        return any(lane.pending for lane in self._lanes)

    def note_solo(self, n: int = 1) -> None:
        with self._stat_mu:
            self._solo_bypass += n

    def commit_fanout(self, ops: list) -> list:
        """Submit one op per drive (None = skip that slot) and wait for
        every ack; returns a per-drive error list aligned with the
        set's disks (None = committed) — the lane-side mirror of
        ErasureSet._fanout's contract for commit fan-outs."""
        n = len(ops)
        dl = deadline_mod.current()
        if dl is not None and dl.expired():
            err = DeadlineExceeded("request deadline exceeded")
            return [err] * n
        members: list[Optional[_Member]] = [None] * n
        latch = _Latch(sum(1 for op in ops if op is not None))
        with self._mu:
            if self._closed:
                from minio_tpu.storage.local import StorageError
                return [StorageError("group commit closed")] * n
            now = time.monotonic()
            wake = False
            for i, op in enumerate(ops):
                if op is None:
                    continue
                m = _Member(op, dl, latch)
                lane = self._lanes[i]
                if not lane.pending:
                    lane.deadline = now + lane.cur_wait
                    lane.min_expiry = m.expires_at
                    wake = True         # a fresh window: (re)arm sleep
                elif m.expires_at is not None and (
                        lane.min_expiry is None
                        or m.expires_at < lane.min_expiry):
                    lane.min_expiry = m.expires_at
                    wake = True         # bound moved earlier
                lane.pending.append(m)
                if len(lane.pending) >= self._max_members                         or len(lane.pending) >= self._inflight:
                    wake = True         # early-close condition met
                members[i] = m
            if self._dispatcher is None or not self._dispatcher.is_alive():
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop, daemon=True,
                    name="group-commit")
                self._dispatcher.start()
                wake = True
            if wake:
                # Waking the dispatcher on EVERY append would make it
                # rescan all lanes per member — O(members x lanes) of
                # pure GIL churn. It only needs to hear about window
                # openings, earlier bounds, and early-close triggers;
                # otherwise its timed sleep already ends at the right
                # moment.
                self._mu.notify_all()
        errors: list = [None] * n
        if dl is None:
            latch.event.wait()
            done = True
        else:
            done = latch.event.wait(timeout=max(
                0.0, dl.expires_at + 0.25 - time.monotonic()))
        for i, m in enumerate(members):
            if m is None:
                continue
            if not done and not m.done:
                # Collection deadline blown with this commit still in
                # flight: mark the straggler; late completions write
                # results nobody reads (same contract as _fanout).
                errors[i] = DeadlineExceeded(
                    "request deadline exceeded in group commit")
                continue
            errors[i] = m.exc
        return errors

    # -- dispatch -------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            with self._mu:
                while not any(ln.pending for ln in self._lanes) \
                        and not self._closed:
                    self._mu.wait(timeout=0.2)
                    if not any(ln.pending for ln in self._lanes) \
                            and self._inflight == 0:
                        # Idle: clear the handle BEFORE dying (under
                        # the lock) so a racing submit starts a fresh
                        # dispatcher instead of trusting a dead one.
                        self._dispatcher = None
                        return
                if self._closed and not any(ln.pending
                                            for ln in self._lanes):
                    self._dispatcher = None
                    return
                now = time.monotonic()
                due = []
                next_bound = None
                for lane in self._lanes:
                    if not lane.pending:
                        continue
                    bound = lane.bound()
                    # Early close: once every group-eligible request in
                    # its commit section has a member on this lane,
                    # nothing more can join before some member leaves —
                    # waiting out the window would buy only latency.
                    if self._closed or now >= bound \
                            or len(lane.pending) >= self._max_members \
                            or len(lane.pending) >= self._inflight:
                        batch, lane.pending = lane.pending, []
                        lane.min_expiry = None
                        due.append((lane, batch))
                    elif next_bound is None or bound < next_bound:
                        next_bound = bound
                if not due:
                    self._mu.wait(timeout=max(0.0, next_bound - now))
                    continue
            for lane, batch in due:
                self._dispatch(lane, batch)

    def _dispatch(self, lane: _Lane, batch: list) -> None:
        """Hand one lane's drained batch to its drive's engine queue
        (wait-vs-service attribution rides the queue's own stats); a
        saturated/closed queue falls back to a fresh thread — a shed
        here would fail every member of the batch, unlike one solo op
        counted against quorum."""
        from minio_tpu.io.engine import EngineSaturated
        fn = lambda: self._run_batch(lane, batch)  # noqa: E731
        try:
            self._io.submit_nowait(lane.idx, fn)
        except EngineSaturated:
            threading.Thread(target=fn, daemon=True,
                             name=f"gc-overflow-{lane.idx}").start()

    def _adapt_window(self, lane: _Lane, size: int) -> None:
        """Coalescing pays per member: batches that actually merge
        stretch the window back toward the base; lone-member windows
        (arrivals slower than the window) shrink it — the early-close
        rule already caps fill at the live concurrency, so the window
        only matters for stragglers mid-submission."""
        if size >= 4:
            lane.cur_wait = min(self._max_wait, lane.cur_wait * 1.5)
        elif size <= 1:
            lane.cur_wait = max(_MIN_WAIT_S, lane.cur_wait * 0.7)

    def _solo(self, disk, op: GroupOp):
        if op.kind == "wm":
            disk.write_metadata(op.volume, op.path, op.fi)
        else:
            disk.rename_data(op.src_volume, op.src_path, op.fi,
                             op.volume, op.path)

    def _run_batch(self, lane: _Lane, batch: list) -> None:
        # Cull members whose budget is already spent: they fail ALONE
        # (DeadlineExceeded, counted) and never poison batch-mates.
        now = time.monotonic()
        live, dead = [], []
        for m in batch:
            if m.expires_at is not None and now >= m.expires_at - 1e-9:
                dead.append(m)
            else:
                live.append(m)
        if dead:
            with self._stat_mu:
                self._deadline_culls += len(dead)
            for m in dead:
                m.exc = DeadlineExceeded(
                    "request deadline exceeded before group commit")
                m.done = True
                m.latch.dec()
        if not live:
            return
        disk = self._disks[lane.idx]
        info: dict = {}
        t_wall = time.time()
        t0 = time.perf_counter()
        results = None
        batch_exc: Optional[BaseException] = None
        try:
            # The batch serves many requests with many budgets; the
            # health wrapper's own op timeout bounds the commit, and
            # the per-member deadlines were enforced at cull time.
            with deadline_mod.shield():
                results = disk.commit_group([m.op for m in live],
                                            _info=info)
        except BaseException as e:  # noqa: BLE001 - delivered per member
            batch_exc = e
        demotions = 0
        for k, m in enumerate(live):
            err = batch_exc if results is None else results[k]
            if err is not None:
                # Member failure (or wholesale batch failure): demote
                # this member — and only it — to the solo path; its
                # own verdict is final.
                demotions += 1
                try:
                    with deadline_mod.shield():
                        self._solo(disk, m.op)
                    err = None
                except BaseException as e2:  # noqa: BLE001 - per member
                    err = e2
            m.exc = err
        dur_ms = (time.perf_counter() - t0) * 1000.0
        size = len(live)
        with self._stat_mu:
            self._batches += 1
            self._members += size
            self._objects += info.get("objects", 0)
            self._merged_members += info.get("merged", 0)
            self._noop_skips += info.get("noops", 0)
            self._fsyncs_saved += info.get("fsyncs_saved", 0)
            self._solo_demotions += demotions
            b = _size_bucket(size)
            self._size_buckets[b] = self._size_buckets.get(b, 0) + 1
        # ONE coalesced invalidation per distinct bucket, BEFORE any
        # member acks: readers that observe the PUT's return must not
        # be able to hit a stale cached fileinfo/listing (the same
        # before-return contract the per-request bump had). Group
        # commit runs on local-only sets, so the bump is an in-process
        # funnel call, never a cross-node push on this thread.
        if self.bump is not None:
            for bucket in sorted({m.op.volume for m in live
                                  if m.exc is None}):
                try:
                    self.bump(bucket)
                except Exception:  # noqa: BLE001 - listeners best-effort
                    pass
        for m in live:
            wait_s = max(0.0, t0 - m.t_enq)
            self._wait_hist.observe(wait_s)
            if m.tctx is not None:
                # ONE commit span fanned into each member's tree.
                tracing.record_into(
                    m.tctx, m.tparent, "storage", "commit.group",
                    t_wall, dur_ms,
                    tags={"drive": lane.name, "members": size,
                          "objects": info.get("objects", 0),
                          "wait_ms": round(wait_s * 1000.0, 3)})
            m.done = True
            m.latch.dec()
        self._adapt_window(lane, size)

    # -- lifecycle / observability --------------------------------------

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()
        # Final WAL checkpoint: a graceful stop leaves no live frames
        # for the next boot to replay; then the WAL fds close.
        for d in self._disks:
            for name in ("gc_checkpoint", "gc_close"):
                fn = getattr(d, name, None)
                if fn is not None:
                    try:
                        fn()
                    except Exception:  # noqa: BLE001 - close best effort
                        pass

    def stats(self) -> dict:
        with self._stat_mu:
            return {
                "name": self.name,
                "batches": self._batches,
                "members": self._members,
                "solo_bypass": self._solo_bypass,
                "objects": self._objects,
                "merged_members": self._merged_members,
                "noop_skips": self._noop_skips,
                "fsyncs_saved": self._fsyncs_saved,
                "deadline_culls": self._deadline_culls,
                "solo_demotions": self._solo_demotions,
                "size_buckets": dict(self._size_buckets),
                "wait_hist": self._wait_hist.state(),
                "fill_mean": (self._members / self._batches)
                if self._batches else 0.0,
            }
