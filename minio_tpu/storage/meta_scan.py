"""Batched xl.meta journal scanning for the listing walk.

The metadata plane's hot loop is "read a few hundred bytes of journal,
extract the handful of fields the walk needs" repeated per object per
walked drive. Doing that with `msgpack.unpackb` + a full `XLMeta` build
costs a Python dict tree and a FileInfo per key; at 10M objects the
interpreter time dwarfs the field extraction. `native/native.cc
mtpu_meta_scan` does the extraction GIL-free over a BATCH of blobs
packed into one pooled buffer; this module owns the batching, the
summary format, and the per-blob fallback to the Python parser for
anything the scanner rejects (counted — watch
minio_tpu_meta_scan_fallback_blobs_total).

Summary format (the walk stream's trimmed entry payload): a tuple of
per-version 8-tuples, latest first, exactly as stored in the journal:

    (flags, mod_time, size, version_id, data_dir, etag, content_type,
     tags)

flags: 1 = delete marker, 2 = inline, 4 = meta-extra (the version's
metadata carries keys beyond etag/content-type/x-amz-tagging, so the
summary cannot rebuild listing metadata by itself — resolution must use
the full journal for this key). Versioned journals longer than
MTPU_META_SCAN_MAXV (default 8) versions are not summarized at all;
they take the full-fidelity path.

A summary is byte-derived only: whichever side produced it (native scan
or `summarize_xl` over a Python-parsed journal), the same blob yields
the same tuple — golden-tested both ways in tests/test_meta_scan.py.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from minio_tpu.storage.meta import XLMeta

FLAG_DELETED = 1
FLAG_INLINE = 2
FLAG_EXTRA = 4

# Shallow-walk subtree marker (walk_scan(shallow=True) yields it in
# place of a summary for a key prefix with evidence of keys below).
PREFIX_MARK = ("__prefix__",)

_CAPTURED_META = ("etag", "content-type", "x-amz-tagging")


def _env_int(key: str, default: int) -> int:
    try:
        v = int(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


MAXV = _env_int("MTPU_META_SCAN_MAXV", 8)
_VSTRIDE = 13
_STRIDE = 2 + _VSTRIDE * MAXV

# Module counters (GIL-atomic +=; aggregated into Prometheus/admin by
# s3/metrics.py): blobs summarized natively vs blobs that took the
# Python parser (scanner rejection, oversized journals, or no native
# lib at all).
counters = {"native": 0, "fallback": 0}

_NATIVE_OFF = os.environ.get("MTPU_META_SCAN", "").lower() in (
    "0", "off", "false")


def _lib():
    if _NATIVE_OFF:
        return None
    from minio_tpu import native
    return native.load()


def summarize_xl(xl: XLMeta, maxv: int = MAXV) -> Optional[tuple]:
    """Summary tuple from a parsed journal — the Python mirror of the
    native scanner, field-identical by construction. None = this
    journal is not summarizable (same cases the native scanner
    rejects: over maxv versions, unknown kinds, missing core fields),
    so both paths classify every blob identically."""
    if len(xl.versions) > maxv:
        return None
    out = []
    for v in xl.versions:
        kind = v.get("kind")
        if kind not in (1, 2):
            return None
        vid, mt = v.get("vid"), v.get("mt")
        if not isinstance(vid, str) or not isinstance(mt, int):
            return None
        flags = FLAG_DELETED if kind == 2 else 0
        if v.get("inline"):
            flags |= FLAG_INLINE
        meta = v.get("meta") or {}
        cap = {}
        for k, val in meta.items():
            if k in _CAPTURED_META and isinstance(val, str):
                cap[k] = val
            else:
                flags |= FLAG_EXTRA
        out.append((flags, mt, v.get("size", 0) or 0, vid,
                    v.get("ddir", "") or "", cap.get("etag", ""),
                    cap.get("content-type", ""),
                    cap.get("x-amz-tagging", "")))
    return tuple(out)


def summary_sufficient(vlist: tuple) -> bool:
    """True when the trimmed summary alone can serve listings for this
    key (no version needs the full journal's metadata)."""
    return all(not (v[0] & FLAG_EXTRA) for v in vlist)


def summary_data_dirs(vlist: tuple) -> frozenset:
    return frozenset(v[4] for v in vlist if v[4])


class BlobScanner:
    """Accumulates xl.meta blobs into one pooled lease and scans them
    in a single native call per batch.

    add(path, fd) reads the (already open) journal straight into the
    pooled buffer — no intermediate bytes object in the common case.
    flush() returns [(path, vlist_or_None, blob_or_None)] in add()
    order: vlist None means the scanner rejected the blob and `blob`
    carries its bytes for the XLMeta.load path; a vlist with any
    meta-extra flag also carries `blob` so resolution can re-read full
    fidelity without another drive round trip.
    """

    # A journal larger than this skips the pooled buffer entirely
    # (giant inline payloads / pathological version counts go straight
    # to the fallback path with their own bytes).
    MAX_POOLED = 256 << 10

    def __init__(self, maxv: int = MAXV, max_items: int = 64,
                 buf_bytes: int = 1 << 20):
        self.maxv = maxv
        self.max_items = max_items
        self.buf_bytes = buf_bytes
        self._lease = None
        self._view = None
        self._fill = 0
        self._items: list = []      # (path, off, end) or (path, None, blob)
        self._lib = _lib()

    # -- feeding -----------------------------------------------------------

    def _ensure_lease(self):
        if self._lease is None:
            from minio_tpu.io.bufpool import global_pool
            self._lease = global_pool().lease(self.buf_bytes)
            self._view = memoryview(self._lease.raw)
            self._fill = 0

    def room(self) -> int:
        size = len(self._view) if self._view is not None else self.buf_bytes
        return size - self._fill

    def full(self) -> bool:
        return len(self._items) >= self.max_items or \
            (self._lease is not None and self.room() < self.MAX_POOLED)

    def add(self, path: str, fd: int) -> None:
        """Read fd's full content into the batch (caller closes fd)."""
        self._ensure_lease()
        space = self.room()
        n = os.preadv(fd, [self._view[self._fill:]], 0)
        if n < 0:
            raise OSError("preadv failed")
        if n == space:
            # Blob may exceed the remaining buffer: slow-path re-read.
            blob = bytearray(self._view[self._fill:self._fill + n])
            while True:
                chunk = os.pread(fd, 1 << 20, len(blob))
                if not chunk:
                    break
                blob += chunk
            self._items.append((path, None, bytes(blob)))
            return
        self._items.append((path, self._fill, self._fill + n))
        self._fill += n

    def add_bytes(self, path: str, blob: bytes) -> None:
        """Stage an already-materialized journal into the pooled batch
        (walk paths that hold bytes rather than open fds — the
        background scanner's merged drive walk): the blob copies into
        the pooled lease so flush()'s ONE native call covers it too.
        Oversized blobs (or a full buffer) take the per-blob fallback
        with their own bytes."""
        n = len(blob)
        if n > self.MAX_POOLED:
            self._items.append((path, None, bytes(blob)))
            return
        self._ensure_lease()
        if n > self.room():
            self._items.append((path, None, bytes(blob)))
            return
        self._view[self._fill:self._fill + n] = blob
        self._items.append((path, self._fill, self._fill + n))
        self._fill += n

    # -- scanning ----------------------------------------------------------

    def _fallback(self, path: str, blob: bytes):
        counters["fallback"] += 1
        try:
            xl = XLMeta.load(blob)
        except Exception:  # noqa: BLE001 - unreadable copy
            return (path, None, blob)
        vlist = summarize_xl(xl, self.maxv)
        if vlist is None:
            return (path, None, blob)
        return (path, vlist, blob if not summary_sufficient(vlist)
                else None)

    def flush(self) -> list:
        if not self._items:
            return []
        items, self._items = self._items, []
        out: list = []
        lib = self._lib
        pooled = [(i, it) for i, it in enumerate(items)
                  if it[1] is not None]
        results: dict[int, tuple] = {}
        if pooled and lib is not None:
            import numpy as np
            nb = len(pooled)
            # Boundary layout for the C call is [o0, o1, ..., on]: blob
            # i is buf[offs[i]:offs[i+1]] — pooled blobs are contiguous
            # in add() order, so boundaries are just the fills.
            bounds = (ctypes.c_int64 * (nb + 1))()
            for j, (_, it) in enumerate(pooled):
                bounds[j] = it[1]
            bounds[nb] = pooled[-1][1][2]
            rec = (ctypes.c_int64 * (_STRIDE * nb))()
            buf = np.frombuffer(self._view, dtype=np.uint8,
                                count=self._fill)
            lib.mtpu_meta_scan(
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                bounds, nb, self.maxv, rec)
            arr = list(rec)
            mv = self._view
            for j, (i, (path, off, end)) in enumerate(pooled):
                base = j * _STRIDE
                status, nver = arr[base], arr[base + 1]
                if status != 0:
                    results[i] = self._fallback(path, bytes(mv[off:end]))
                    continue
                counters["native"] += 1
                vlist = []
                suff = True
                for v in range(nver):
                    o = base + 2 + _VSTRIDE * v
                    flags = arr[o]
                    if flags & FLAG_EXTRA:
                        suff = False

                    def s(slot):
                        a, ln = arr[o + slot], arr[o + slot + 1]
                        return mv[a:a + ln].tobytes().decode(
                            "utf-8", "surrogateescape") if ln else ""
                    vlist.append((flags, arr[o + 1], arr[o + 2],
                                  s(3), s(5), s(7), s(9), s(11)))
                results[i] = (path, tuple(vlist),
                              None if suff else bytes(mv[off:end]))
        elif pooled:
            for i, (path, off, end) in pooled:
                results[i] = self._fallback(
                    path, bytes(self._view[off:end]))
        for i, it in enumerate(items):
            if it[1] is None:
                out.append(self._fallback(it[0], it[2]))
            else:
                out.append(results[i])
        self._fill = 0
        return out

    def close(self) -> None:
        self._items = []
        self._view = None
        if self._lease is not None:
            self._lease.release()
            self._lease = None


def scan_blob(blob: bytes, maxv: int = MAXV) -> Optional[tuple]:
    """Single-blob summary (shallow listing walks, tests): native when
    available, Python mirror otherwise; None when not summarizable."""
    lib = _lib()
    if lib is not None:
        nb = 1
        bounds = (ctypes.c_int64 * 2)(0, len(blob))
        rec = (ctypes.c_int64 * _STRIDE)()
        cbuf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        if lib.mtpu_meta_scan(
                ctypes.cast(cbuf, ctypes.POINTER(ctypes.c_uint8)),
                bounds, nb, maxv, rec) == 1:
            counters["native"] += 1
            vlist = []
            for v in range(rec[1]):
                o = 2 + _VSTRIDE * v

                def s(slot):
                    a, ln = rec[o + slot], rec[o + slot + 1]
                    return bytes(cbuf[a:a + ln]).decode(
                        "utf-8", "surrogateescape") if ln else ""
                vlist.append((rec[o], rec[o + 1], rec[o + 2],
                              s(3), s(5), s(7), s(9), s(11)))
            return tuple(vlist)
        counters["fallback"] += 1
        try:
            return summarize_xl(XLMeta.load(blob), maxv)
        except Exception:  # noqa: BLE001 - unreadable blob
            return None
    counters["fallback"] += 1
    try:
        return summarize_xl(XLMeta.load(blob), maxv)
    except Exception:  # noqa: BLE001 - unreadable blob
        return None
