"""Remote StorageAPI: drives living in other node processes.

The analogue of the reference's storage REST layer
(cmd/storage-rest-client.go / cmd/storage-rest-server.go, paths
cmd/storage-rest-common.go:29-47): `RemoteStorage` implements the same
drive interface as LocalStorage but forwards every call over the grid
mesh to the node that owns the drive; `StorageRPCService` is the server
side, exposing a set of local drives. Storage exceptions round-trip by
code so quorum logic upstream cannot tell local and remote faults
apart. Bulk byte ops (create_file / read_file) chunk through the same
muxed connection — the grid frame cap bounds head-of-line blocking
(reference splits these onto HTTP streams; one muxed pipe with bounded
frames achieves the same isolation here).
"""

from __future__ import annotations

import os
import time
from types import SimpleNamespace
from typing import Iterator, Optional

from minio_tpu.grid import GridError, RemoteCallError, client_for
from minio_tpu.grid import wire
from minio_tpu.grid.server import GridServer, register_error
from minio_tpu.storage.local import (DiskAccessDenied, DiskInfo, LocalStorage,
                                     StorageError, VolInfo, VolumeExists,
                                     VolumeNotEmpty, VolumeNotFound)
from minio_tpu.storage.meta import (FileInfo, FileNotFoundErr, MetaError,
                                    VersionNotFoundErr, fi_from_wire,
                                    fi_to_wire)
from minio_tpu.utils import tracing

# Bulk transfers chunk at this size (small enough to interleave with
# lock/metadata frames on the shared connection).
# Bulk transfer chunk: one grid frame per chunk. Kept to 1 MiB so lock
# and metadata RPCs interleave between a big transfer's frames instead
# of waiting behind one multi-MiB sendall (the write lock is per frame).
CHUNK = 1 << 20

# walk_scan wire entry kinds: [path, kind, payload...] per entry.
_WS_SUMMARY = 0        # [path, 0, vlist]            trimmed summary
_WS_SUMMARY_BLOB = 1   # [path, 1, vlist, blob]      summary + journal
_WS_BLOB = 2           # [path, 2, blob]             scanner fallback
_WS_MARK = 3           # [path, 3]                   shallow prefix mark

_CODE_TO_EXC = {
    "FileNotFound": FileNotFoundErr,
    "VersionNotFound": VersionNotFoundErr,
    "VolumeNotFound": VolumeNotFound,
    "VolumeExists": VolumeExists,
    "VolumeNotEmpty": VolumeNotEmpty,
    "DiskAccessDenied": DiskAccessDenied,
    "MetaError": MetaError,
    "StorageError": StorageError,
}
for code, exc in _CODE_TO_EXC.items():
    register_error(exc, code)


def _raise_mapped(e: RemoteCallError):
    exc = _CODE_TO_EXC.get(e.code)
    if exc is not None:
        raise exc(str(e)) from None
    raise StorageError(str(e)) from None


class RemoteStorage:
    """Drive client: same surface as LocalStorage, calls ride the grid."""

    def __init__(self, host: str, port: int, root: str):
        self.host = host
        self.port = port
        self.root = root
        self.endpoint = f"http://{host}:{port}{root}"

    def _call(self, method: str, *args, timeout: Optional[float] = None):
        c = client_for(self.host, self.port)
        try:
            return c.call("st." + method, {"d": self.root, "a": list(args)},
                          timeout=timeout)
        except RemoteCallError as e:
            _raise_mapped(e)
        except GridError as e:
            raise StorageError(f"remote drive {self.endpoint}: {e}") from None

    # -- identity ------------------------------------------------------

    def read_format(self):
        return self._call("read_format")

    def write_format(self, fmt: dict) -> None:
        self._call("write_format", fmt)

    def disk_id(self) -> str:
        return self._call("disk_id")

    def is_online(self) -> bool:
        try:
            return bool(self._call("is_online", timeout=3.0))
        except StorageError:
            return False

    # -- volumes -------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        self._call("make_vol", volume)

    def make_vol_if_missing(self, volume: str) -> None:
        self._call("make_vol_if_missing", volume)

    def list_vols(self) -> list[VolInfo]:
        return [VolInfo(name=v["name"], created=v["created"])
                for v in self._call("list_vols")]

    def stat_vol(self, volume: str) -> VolInfo:
        v = self._call("stat_vol", volume)
        return VolInfo(name=v["name"], created=v["created"])

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call("delete_vol", volume, force)

    # -- raw files -----------------------------------------------------

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("write_all", volume, path, bytes(data))

    def read_all(self, volume: str, path: str) -> bytes:
        return self._call("read_all", volume, path)

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call("delete", volume, path, recursive)

    # -- shard files (bulk; chunked over the mux) ----------------------

    # Credit window: chunks in flight per transfer. Bounds the frames a
    # bulk sender can queue ahead of lock traffic (the reference's grid
    # uses credit-based flow control on its bulk streams) while
    # overlapping the per-chunk round-trip latency that a strict
    # stop-and-wait pays in full.
    WINDOW = 4

    def create_file(self, volume: str, path: str, data) -> None:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = b"".join(data)
        data = bytes(data)
        if len(data) <= CHUNK:
            self._call("create_file", volume, path, data)
            return
        if wire.native_enabled():
            # Native plane: one flow-controlled push stream of raw
            # frames (no msgpack wrap, no per-chunk copies) staged and
            # committed by the receiver — replaces the windowed
            # create_begin/create_chunk/create_commit round-trips.
            c = client_for(self.host, self.port)
            try:
                c.push_raw("st.write_file_raw",
                           {"d": self.root, "a": [volume, path]},
                           [memoryview(data)])
                return
            except RemoteCallError as e:
                _raise_mapped(e)
            except GridError as e:
                raise StorageError(
                    f"remote drive {self.endpoint}: {e}") from None
        # Chunked upload: stage under a transfer id, commit on finish.
        # Chunks carry their OFFSET so the windowed sends may complete
        # out of order on the receiver. WINDOW worker threads drain an
        # offset queue (not a thread per chunk — a 1 GiB shard would
        # otherwise create ~1024 short-lived threads).
        import queue as queue_mod
        import threading
        xfer = self._call("create_begin", volume, path)
        offsets: "queue_mod.Queue" = queue_mod.Queue()
        for off in range(0, len(data), CHUNK):
            offsets.put(off)
        errors: list = []

        def worker() -> None:
            while not errors:
                try:
                    off = offsets.get_nowait()
                except queue_mod.Empty:
                    return
                try:
                    self._call("create_chunk", xfer, off,
                               data[off:off + CHUNK])
                except Exception as e:  # noqa: BLE001 - surfaced below
                    errors.append(e)
                    return

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.WINDOW, offsets.qsize()))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        self._call("create_commit", xfer)

    def read_file(self, volume: str, path: str, offset: int = 0,
                  length: int = -1) -> bytes:
        c = client_for(self.host, self.port)
        if 0 <= length <= CHUNK and wire.native_enabled():
            # Small explicit-length read (the GET path's bitrot-framed
            # block windows): one unary round-trip — no stream
            # open/close, no credit machinery, the write-side twin of
            # create_file's <= CHUNK branch. Falls back to the stream
            # against an older peer that lacks the verb.
            try:
                return self._call("read_file", volume, path, offset,
                                  length)
            except StorageError as e:
                if "NoSuchHandler" not in str(e):
                    raise
        try:
            if wire.native_enabled():
                # Native plane: the peer ships the shard file straight
                # from its drive fd via os.sendfile (zero Python-level
                # copies send-side); raw frames land here in pooled
                # leases and are assembled once into the result.
                return self._read_file_native(c, volume, path, offset,
                                              length)
            parts = list(c.stream("st.read_file_stream",
                                  {"d": self.root, "a": [volume, path,
                                                         offset, length]}))
        except RemoteCallError as e:
            _raise_mapped(e)
        except GridError as e:
            raise StorageError(f"remote drive {self.endpoint}: {e}") from None
        return b"".join(parts)

    def _read_file_native(self, c, volume: str, path: str, offset: int,
                          length: int) -> bytes:
        out: Optional[bytearray] = None
        pos = 0
        spill = bytearray()
        for item in c.stream("st.read_file_raw",
                             {"d": self.root,
                              "a": [volume, path, offset, length]},
                             raw=True):
            if isinstance(item, tuple):          # raw frame: (view, lease)
                view, lease = item
                try:
                    if out is not None and pos + len(view) <= len(out):
                        out[pos:pos + len(view)] = view
                        pos += len(view)
                    else:
                        spill += view
                finally:
                    if lease is not None:
                        lease.release()
            elif isinstance(item, dict) and "size" in item:
                # Size header: preallocate the result once instead of
                # growing a bytearray per frame.
                out = bytearray(int(item["size"]))
            elif item:                           # v1 peer: plain bytes
                spill += item
        if out is None:
            return bytes(spill)
        if spill:
            return bytes(out[:pos]) + bytes(spill)
        return bytes(out[:pos]) if pos != len(out) else bytes(out)

    def stat_info_file(self, volume: str, path: str):
        st = self._call("stat_info_file", volume, path)
        return SimpleNamespace(st_size=st["size"], st_mtime=st["mtime"])

    # -- versioned metadata --------------------------------------------

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("write_metadata", volume, path, fi_to_wire(fi))

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("update_metadata", volume, path, fi_to_wire(fi))

    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo:
        return fi_from_wire(self._call("read_version", volume, path,
                                       version_id, read_data))

    def read_xl(self, volume: str, path: str) -> bytes:
        return self._call("read_xl", volume, path)

    def list_versions(self, volume: str, path: str) -> list[FileInfo]:
        return [fi_from_wire(d)
                for d in self._call("list_versions", volume, path)]

    def delete_version(self, volume: str, path: str, version_id: str = "",
                       force_del_marker: bool = False) -> None:
        self._call("delete_version", volume, path, version_id,
                   force_del_marker)

    # -- commit protocol -----------------------------------------------

    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        self._call("rename_data", src_volume, src_path, fi_to_wire(fi),
                   dst_volume, dst_path)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        self._call("rename_file", src_volume, src_path, dst_volume, dst_path)

    # -- listing -------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        return self._call("list_dir", volume, dir_path, count)

    def walk_scan(self, volume: str, base_dir: str = "",
                  forward_from: str = "", shallow: bool = False):
        """The trimmed listing walk over the grid: the remote node runs
        its local batched-native walk_scan (storage/local.py) and ships
        only the SUMMARY tuples — at 10M objects the difference versus
        walk_dir's full xl.meta journals is the whole metadata plane's
        PR-8 win, now available to distributed sets. Yields the same
        (path, vlist, blob) triples the local generator does, including
        the PREFIX_MARK sentinel for shallow delimiter pages."""
        from minio_tpu.storage.meta_scan import PREFIX_MARK
        c = client_for(self.host, self.port)
        try:
            for batch in c.stream("st.walk_scan",
                                  {"d": self.root,
                                   "a": [volume, base_dir, forward_from,
                                         bool(shallow)]}):
                for ent in batch:
                    path, kind = ent[0], ent[1]
                    if kind == _WS_MARK:
                        yield path, PREFIX_MARK, None
                    elif kind == _WS_BLOB:
                        yield path, None, ent[2]
                    else:
                        # Canonical tuple-of-tuples form — identical to
                        # what the local generator yields, so resolver
                        # agreement sets and metacache entries never
                        # see a list/tuple split across drive kinds.
                        vlist = tuple(tuple(v) for v in ent[2])
                        blob = ent[3] if kind == _WS_SUMMARY_BLOB else None
                        yield path, vlist, blob
        except RemoteCallError as e:
            _raise_mapped(e)
        except GridError as e:
            raise StorageError(f"remote drive {self.endpoint}: {e}") from None

    def walk_dir(self, volume: str, base_dir: str = "",
                 recursive: bool = True,
                 forward_from: str = "") -> Iterator[tuple[str, bytes]]:
        c = client_for(self.host, self.port)
        try:
            for batch in c.stream("st.walk_dir",
                                  {"d": self.root,
                                   "a": [volume, base_dir, recursive,
                                         forward_from]}):
                for path, blob in batch:
                    yield path, blob
        except RemoteCallError as e:
            _raise_mapped(e)
        except GridError as e:
            raise StorageError(f"remote drive {self.endpoint}: {e}") from None

    # -- health --------------------------------------------------------

    def disk_info(self) -> DiskInfo:
        d = self._call("disk_info")
        return DiskInfo(**d)


def _span_unary(name: str, fn):
    """Serving-side span for an armed caller: the grid runner executes
    the handler bound to the shipped trace context, so recording here
    lands `disk.<op>` in the subtree that piggybacks home. Disarmed
    cost is one attribute check."""
    def handler(payload):
        if not tracing.ACTIVE:
            return fn(payload)
        tags = {"drive": payload.get("d", "")} \
            if isinstance(payload, dict) else None
        with tracing.span("storage", f"disk.{name}", tags):
            return fn(payload)
    return handler


def _span_stream(name: str, fn):
    """Stream twin of _span_unary: the span covers the generator's
    whole life (first pull to exhaustion), recorded when it closes —
    before the EOF frame ships the subtree."""
    def handler(payload):
        if not tracing.ACTIVE:
            yield from fn(payload)
            return
        tags = {"drive": payload.get("d", "")} \
            if isinstance(payload, dict) else None
        with tracing.span("storage", f"disk.{name}", tags):
            yield from fn(payload)
    return handler


class StorageRPCService:
    """Server side: exposes this node's local drives over the grid."""

    _UNARY = (
        "read_format write_format disk_id is_online make_vol "
        "make_vol_if_missing delete_vol write_all read_all delete "
        "create_file read_file stat_info_file read_xl delete_version "
        "rename_file list_dir"
    ).split()

    # Chunked uploads whose client died between create_begin and
    # create_commit would otherwise leak an open fd + tmp file forever.
    XFER_IDLE_TTL = 300.0

    def __init__(self, disks: dict[str, LocalStorage],
                 xfer_idle_ttl: float = XFER_IDLE_TTL):
        self.disks = dict(disks)     # root path -> LocalStorage
        self._xfers: dict[str, dict] = {}
        self.xfer_idle_ttl = xfer_idle_ttl
        import threading
        self._xfer_mu = threading.Lock()

    def _sweep_stale_xfers(self) -> None:
        now = time.monotonic()
        stale = []
        with self._xfer_mu:
            for xfer, st in list(self._xfers.items()):
                if now - st["touched"] > self.xfer_idle_ttl:
                    stale.append(self._xfers.pop(xfer))
        for st in stale:
            try:
                st["f"].close()
            except OSError:
                pass
            try:
                os.unlink(st["tmp"])
            except OSError:
                pass

    def _disk(self, payload: dict) -> LocalStorage:
        # Cluster-harness chaos: a "hung remote drive" sleeps here —
        # every storage RPC funnels through this lookup (the in-process
        # twin is tests/chaos.HungDisk; this reaches spawned nodes).
        from minio_tpu.grid import chaos
        delay = chaos.drive_delay()
        if delay > 0:
            time.sleep(delay)
        d = self.disks.get(payload.get("d", ""))
        if d is None:
            raise StorageError(f"no such drive: {payload.get('d')!r}")
        return d

    def register_into(self, srv: GridServer) -> None:
        for name in self._UNARY:
            srv.register(f"st.{name}",
                         _span_unary(name, self._make_unary(name)))
        srv.register("st.stat_vol", _span_unary("stat_vol",
                                                self._stat_vol))
        srv.register("st.list_vols", _span_unary("list_vols",
                                                 self._list_vols))
        srv.register("st.write_metadata", _span_unary(
            "write_metadata", self._meta_op("write_metadata")))
        srv.register("st.update_metadata", _span_unary(
            "update_metadata", self._meta_op("update_metadata")))
        srv.register("st.read_version",
                     _span_unary("read_version", self._read_version))
        srv.register("st.list_versions",
                     _span_unary("list_versions", self._list_versions))
        srv.register("st.rename_data",
                     _span_unary("rename_data", self._rename_data))
        srv.register("st.disk_info",
                     _span_unary("disk_info", self._disk_info))
        srv.register("st.create_begin",
                     _span_unary("create_begin", self._create_begin))
        srv.register("st.create_chunk",
                     _span_unary("create_chunk", self._create_chunk))
        srv.register("st.create_commit",
                     _span_unary("create_commit", self._create_commit))
        srv.register_stream("st.read_file_stream", _span_stream(
            "read_file_stream", self._read_file_stream))
        srv.register_stream("st.read_file_raw", _span_stream(
            "read_file_raw", self._read_file_raw))
        srv.register_sink("st.write_file_raw", self._write_file_raw)
        srv.register_stream("st.walk_dir",
                            _span_stream("walk_dir", self._walk_dir))
        srv.register_stream("st.walk_scan",
                            _span_stream("walk_scan", self._walk_scan))

    def _make_unary(self, name: str):
        def handler(payload):
            d = self._disk(payload)
            out = getattr(d, name)(*payload.get("a", ()))
            if name == "stat_info_file":
                return {"size": out.st_size, "mtime": out.st_mtime}
            return out
        return handler

    def _stat_vol(self, payload):
        v = self._disk(payload).stat_vol(*payload["a"])
        return {"name": v.name, "created": v.created}

    def _list_vols(self, payload):
        return [{"name": v.name, "created": v.created}
                for v in self._disk(payload).list_vols()]

    def _meta_op(self, name: str):
        def handler(payload):
            vol, path, fid = payload["a"]
            getattr(self._disk(payload), name)(vol, path, fi_from_wire(fid))
        return handler

    def _read_version(self, payload):
        return fi_to_wire(self._disk(payload).read_version(*payload["a"]))

    def _list_versions(self, payload):
        return [fi_to_wire(fi)
                for fi in self._disk(payload).list_versions(*payload["a"])]

    def _rename_data(self, payload):
        src_vol, src_path, fid, dst_vol, dst_path = payload["a"]
        self._disk(payload).rename_data(src_vol, src_path, fi_from_wire(fid),
                                        dst_vol, dst_path)

    def _disk_info(self, payload):
        di = self._disk(payload).disk_info()
        return {"total": di.total, "free": di.free, "used": di.used,
                "root_disk": di.root_disk, "healing": di.healing,
                "endpoint": di.endpoint, "disk_id": di.disk_id,
                "error": di.error}

    # chunked create_file: stage in tmp, atomic finish -----------------

    def _create_begin(self, payload):
        from minio_tpu.storage.meta import new_uuid
        self._sweep_stale_xfers()
        d = self._disk(payload)
        vol, path = payload["a"]
        xfer = new_uuid()
        tmp = d._tmp_path()
        os.makedirs(os.path.dirname(tmp), exist_ok=True)
        import threading as _threading
        with self._xfer_mu:
            self._xfers[xfer] = {"disk": d, "vol": vol, "path": path,
                                 "tmp": tmp, "f": open(tmp, "wb"),
                                 "mu": _threading.Lock(),
                                 "touched": time.monotonic()}
        return xfer

    def _create_chunk(self, payload):
        # (xfer, offset, data): offset-addressed so the sender's credit
        # window may deliver chunks out of order; the 2-tuple
        # (xfer, data) append form is also accepted. NOTE: the grid
        # wire protocol carries no cross-version compatibility
        # contract — every node in a deployment runs the same build
        # (same as the reference's internal REST APIs).
        args = payload["a"]
        if len(args) == 3:
            xfer, off, data = args
        else:
            xfer, data = args
            off = None
        with self._xfer_mu:
            st = self._xfers.get(xfer)
            if st is not None:
                st["touched"] = time.monotonic()
        if st is None:
            raise StorageError(f"no such transfer {xfer}")
        with st["mu"]:
            if off is not None:
                st["f"].seek(off)
            st["f"].write(data)

    def _create_commit(self, payload):
        (xfer,) = payload["a"]
        with self._xfer_mu:
            st = self._xfers.pop(xfer, None)
        if st is None:
            raise StorageError(f"no such transfer {xfer}")
        f = st["f"]
        f.flush()
        os.fsync(f.fileno())
        f.close()
        d: LocalStorage = st["disk"]
        dest = d._obj_dir(st["vol"], st["path"])
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        os.replace(st["tmp"], dest)

    # streams ----------------------------------------------------------

    def _read_file_stream(self, payload):
        d = self._disk(payload)
        vol, path, offset, length = payload["a"]
        blob = d.read_file(vol, path, offset=offset, length=length)
        for off in range(0, len(blob), CHUNK):
            yield blob[off:off + CHUNK]
        if not blob:
            yield b""

    def _read_file_raw(self, payload):
        """Zero-copy shard read: a size header, then the file region as
        raw frames shipped by the server send path via os.sendfile —
        the bitrot-framed shard bytes never surface into this process.
        Byte-identical to read_file_stream (both are the raw file
        content at [offset, offset+length))."""
        d = self._disk(payload)
        vol, path, offset, length = payload["a"]
        full = d._obj_dir(vol, path)
        try:
            size = os.path.getsize(full)
        except OSError:
            raise FileNotFoundErr(f"{vol}/{path}") from None
        offset = max(0, int(offset or 0))
        if length is None or length < 0:
            length = max(0, size - offset)
        else:
            length = max(0, min(int(length), size - offset))
        yield {"size": length}
        yield wire.RawFile(full, offset, length)

    def _write_file_raw(self, payload, frames):
        """Zero-copy shard write: pushed raw frames land in pooled
        leases and are written straight into a staging file, then
        fsynced and atomically renamed — the receiver half of the
        native create_file path (same durability as LocalStorage
        create_file + the msgpack create_commit)."""
        d = self._disk(payload)
        vol, path = payload["a"]
        tmp = d._tmp_path()
        os.makedirs(os.path.dirname(tmp), exist_ok=True)
        try:
            with open(tmp, "wb") as f:
                for chunk in frames:
                    if chunk:
                        f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            dest = d._obj_dir(vol, path)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            os.replace(tmp, dest)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True

    def _walk_dir(self, payload):
        d = self._disk(payload)
        vol, base_dir, recursive, forward_from = payload["a"]
        batch: list = []
        size = 0
        for path, blob in d.walk_dir(vol, base_dir=base_dir,
                                     recursive=recursive,
                                     forward_from=forward_from):
            batch.append([path, blob])
            size += len(blob) + len(path)
            if len(batch) >= 128 or size >= CHUNK:
                yield batch
                batch, size = [], 0
        if batch:
            yield batch

    def _walk_scan(self, payload):
        """Trimmed listing walk: stream the local walk_scan's summary
        entries in batched frames — summaries are tens of bytes per
        version, so one frame carries hundreds of keys where _walk_dir
        carried a handful of full journals."""
        from minio_tpu.storage.meta_scan import PREFIX_MARK
        d = self._disk(payload)
        vol, base_dir, forward_from, shallow = payload["a"]
        ws = getattr(d, "walk_scan", None)
        if ws is None:
            raise StorageError("drive does not support walk_scan")
        batch: list = []
        size = 0
        for path, vlist, blob in ws(vol, base_dir=base_dir,
                                    forward_from=forward_from,
                                    shallow=bool(shallow)):
            if vlist is PREFIX_MARK:
                ent = [path, _WS_MARK]
                size += len(path) + 8
            elif vlist is None:
                ent = [path, _WS_BLOB, blob]
                size += len(path) + len(blob or b"")
            elif blob is not None:
                ent = [path, _WS_SUMMARY_BLOB,
                       [list(v) for v in vlist], blob]
                size += len(path) + len(blob) + 64 * len(vlist)
            else:
                ent = [path, _WS_SUMMARY, [list(v) for v in vlist]]
                size += len(path) + 64 * len(vlist)
            batch.append(ent)
            if len(batch) >= 512 or size >= CHUNK:
                yield batch
                batch, size = [], 0
        if batch:
            yield batch
