"""Bitrot protection: algorithms, golden self-test, streaming shard format.

Mirrors the reference's bitrot layer (cmd/bitrot.go): four algorithms
(SHA256, BLAKE2b-512, HighwayHash-256 whole-file, HighwayHash-256S
streamed), the keyed-HighwayHash default, and the streaming shard-file
framing `hash || shard_block` repeated per erasure block
(cmd/bitrot-streaming.go:44-75). The self-test reproduces the
reference's boot gate byte for byte (cmd/bitrot.go:224-255) — a mismatch
means we would silently corrupt data, so callers treat it as fatal.

The HighwayHash core is ours (minio_tpu/utils/highwayhash.py, vectorized
across shard streams); SHA-256 / BLAKE2b come from hashlib (OpenSSL),
exactly as the reference takes them from crypto libraries.
"""

from __future__ import annotations

import hashlib
from typing import Callable

import numpy as np

from minio_tpu.utils.highwayhash import (MAGIC_KEY, highwayhash256,
                                         highwayhash256_many)

# Algorithm names follow the reference's wire/disk identifiers
# (cmd/bitrot.go:39-44) so xl.meta stays interoperable in spirit.
SHA256 = "sha256"
BLAKE2B512 = "blake2b"
HIGHWAYHASH256 = "highwayhash256"
HIGHWAYHASH256S = "highwayhash256S"

DEFAULT_ALGORITHM = HIGHWAYHASH256S  # reference: cmd/bitrot.go:105-110

_ALGORITHMS: dict[str, tuple[int, Callable[[bytes], bytes]]] = {
    SHA256: (32, lambda data: hashlib.sha256(data).digest()),
    BLAKE2B512: (64, lambda data: hashlib.blake2b(data, digest_size=64).digest()),
    HIGHWAYHASH256: (32, lambda data: highwayhash256(MAGIC_KEY, data)),
    HIGHWAYHASH256S: (32, lambda data: highwayhash256(MAGIC_KEY, data)),
}

# hash.Hash.BlockSize() of each algorithm in the reference's Go stdlib
# sense — only used to reproduce the self-test message schedule.
_SELFTEST_BLOCKSIZE = {SHA256: 64, BLAKE2B512: 128,
                       HIGHWAYHASH256: 32, HIGHWAYHASH256S: 32}

# Golden digests from the reference's bitrotSelfTest (cmd/bitrot.go:225-230).
_GOLDEN = {
    SHA256: "a7677ff19e0182e4d52e3a3db727804abc82a5818749336369552e54b838b004",
    BLAKE2B512: ("e519b7d84b1c3c917985f544773a35cf265dcab10948be3550320d156bab6121"
                 "24a5ae2ae5a8c73c0eea360f68b0e28136f26e858756dbfe7375a7389f26c669"),
    HIGHWAYHASH256: "39c0407ed3f01b18d22c85db4aeff11e060ca5f43131b0126731ca197cd42313",
    HIGHWAYHASH256S: "39c0407ed3f01b18d22c85db4aeff11e060ca5f43131b0126731ca197cd42313",
}


def available(algorithm: str) -> bool:
    return algorithm in _ALGORITHMS


def digest_size(algorithm: str) -> int:
    return _ALGORITHMS[algorithm][0]


def hash_block(algorithm: str, data: bytes | np.ndarray) -> bytes:
    """One-shot digest of a shard block."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    return _ALGORITHMS[algorithm][1](data)


def hash_blocks_many(algorithm: str, blocks: np.ndarray) -> np.ndarray:
    """Digest S equal-length shard blocks: uint8 [S, L] -> uint8 [S, size].

    HighwayHash uses the vectorized lockstep core (the bitrot hot path);
    the rare non-default algorithms loop per stream.
    """
    if algorithm in (HIGHWAYHASH256, HIGHWAYHASH256S):
        return highwayhash256_many(MAGIC_KEY, blocks)
    size = digest_size(algorithm)
    out = np.empty((blocks.shape[0], size), dtype=np.uint8)
    for i in range(blocks.shape[0]):
        out[i] = np.frombuffer(hash_block(algorithm, blocks[i]), dtype=np.uint8)
    return out


class SelfTestError(Exception):
    """A bitrot digest differs from the reference. Fatal at boot."""


def bitrot_self_test() -> None:
    """Reproduces the reference's boot-time golden check (cmd/bitrot.go:232-254).

    Schedule: starting from an empty message, repeat size*blocksize/size
    times: digest the message, append the digest to the message. The final
    digest must equal the golden value.
    """
    for algorithm, want_hex in _GOLDEN.items():
        size = digest_size(algorithm)
        rounds = _SELFTEST_BLOCKSIZE[algorithm]
        msg = b""
        sum_ = b""
        for _ in range(0, size * rounds, size):
            sum_ = hash_block(algorithm, msg)
            msg += sum_
        if sum_.hex() != want_hex:
            raise SelfTestError(
                f"bitrot self-test {algorithm}: got {sum_.hex()}, want {want_hex}")
