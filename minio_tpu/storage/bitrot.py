"""Bitrot protection: algorithms, golden self-test, streaming shard format.

Mirrors the reference's bitrot layer (cmd/bitrot.go): four algorithms
(SHA256, BLAKE2b-512, HighwayHash-256 whole-file, HighwayHash-256S
streamed), the keyed-HighwayHash default, and the streaming shard-file
framing `hash || shard_block` repeated per erasure block
(cmd/bitrot-streaming.go:44-75). The self-test reproduces the
reference's boot gate byte for byte (cmd/bitrot.go:224-255) — a mismatch
means we would silently corrupt data, so callers treat it as fatal.

The HighwayHash core is ours (minio_tpu/utils/highwayhash.py, vectorized
across shard streams); SHA-256 / BLAKE2b come from hashlib (OpenSSL),
exactly as the reference takes them from crypto libraries.
"""

from __future__ import annotations

import hashlib
from typing import Callable

import numpy as np

from minio_tpu.utils.highwayhash import (MAGIC_KEY, highwayhash256,
                                         highwayhash256_many)

# Algorithm names follow the reference's wire/disk identifiers
# (cmd/bitrot.go:39-44) so xl.meta stays interoperable in spirit.
SHA256 = "sha256"
BLAKE2B512 = "blake2b"
HIGHWAYHASH256 = "highwayhash256"
HIGHWAYHASH256S = "highwayhash256S"

DEFAULT_ALGORITHM = HIGHWAYHASH256S  # reference: cmd/bitrot.go:105-110

_ALGORITHMS: dict[str, tuple[int, Callable[[bytes], bytes]]] = {
    SHA256: (32, lambda data: hashlib.sha256(data).digest()),
    BLAKE2B512: (64, lambda data: hashlib.blake2b(data, digest_size=64).digest()),
    HIGHWAYHASH256: (32, lambda data: highwayhash256(MAGIC_KEY, data)),
    HIGHWAYHASH256S: (32, lambda data: highwayhash256(MAGIC_KEY, data)),
}

# hash.Hash.BlockSize() of each algorithm in the reference's Go stdlib
# sense — only used to reproduce the self-test message schedule.
_SELFTEST_BLOCKSIZE = {SHA256: 64, BLAKE2B512: 128,
                       HIGHWAYHASH256: 32, HIGHWAYHASH256S: 32}

# Golden digests from the reference's bitrotSelfTest (cmd/bitrot.go:225-230).
_GOLDEN = {
    SHA256: "a7677ff19e0182e4d52e3a3db727804abc82a5818749336369552e54b838b004",
    BLAKE2B512: ("e519b7d84b1c3c917985f544773a35cf265dcab10948be3550320d156bab6121"
                 "24a5ae2ae5a8c73c0eea360f68b0e28136f26e858756dbfe7375a7389f26c669"),
    HIGHWAYHASH256: "39c0407ed3f01b18d22c85db4aeff11e060ca5f43131b0126731ca197cd42313",
    HIGHWAYHASH256S: "39c0407ed3f01b18d22c85db4aeff11e060ca5f43131b0126731ca197cd42313",
}


def available(algorithm: str) -> bool:
    return algorithm in _ALGORITHMS


def digest_size(algorithm: str) -> int:
    return _ALGORITHMS[algorithm][0]


def hash_block(algorithm: str, data: bytes | np.ndarray) -> bytes:
    """One-shot digest of a shard block."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    return _ALGORITHMS[algorithm][1](data)


def hash_blocks_many(algorithm: str, blocks: np.ndarray) -> np.ndarray:
    """Digest S equal-length shard blocks: uint8 [S, L] -> uint8 [S, size].

    HighwayHash uses the vectorized lockstep core (the bitrot hot path);
    the rare non-default algorithms loop per stream.
    """
    if algorithm in (HIGHWAYHASH256, HIGHWAYHASH256S):
        return highwayhash256_many(MAGIC_KEY, blocks)
    size = digest_size(algorithm)
    out = np.empty((blocks.shape[0], size), dtype=np.uint8)
    for i in range(blocks.shape[0]):
        out[i] = np.frombuffer(hash_block(algorithm, blocks[i]), dtype=np.uint8)
    return out


def shard_file_size(size: int, shard_size: int, algorithm: str = DEFAULT_ALGORITHM) -> int:
    """On-disk size of a bitrot-framed shard file (reference:
    bitrotShardFileSize, cmd/bitrot.go:156-161): one digest per shard
    block plus the data itself; whole-file algorithms store bare data."""
    if algorithm != HIGHWAYHASH256S:
        return size
    if size < 0:
        return -1
    from minio_tpu.erasure.codec import ceil_frac
    return ceil_frac(size, shard_size) * digest_size(algorithm) + size


def frame_shard(shard: np.ndarray, shard_size: int,
                algorithm: str = DEFAULT_ALGORITHM) -> bytes:
    """Frame one shard file: `digest || block` per shard_size block
    (reference: streamingBitrotWriter.Write, cmd/bitrot-streaming.go:44-75)."""
    shard = np.ascontiguousarray(shard, dtype=np.uint8)
    n = shard.shape[0]
    hsize = digest_size(algorithm)
    out = bytearray()
    for off in range(0, n, shard_size):
        block = shard[off:off + shard_size]
        out += hash_block(algorithm, block)
        out += block.tobytes()
    return bytes(out)


def frame_shards_batch(shards: np.ndarray, shard_size: int,
                       algorithm: str = DEFAULT_ALGORITHM) -> list[bytes]:
    """Frame all n shards of one object at once: uint8 [n, L] -> n files.

    All full blocks across all shards hash in ONE vectorized lockstep pass
    (n * n_blocks streams), the ragged tail in a second — the host-side
    shape of the reference's per-shard-block hashing, batched.
    """
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    n, length = shards.shape
    if length == 0:
        return [b""] * n
    full = length // shard_size
    tail = length - full * shard_size
    digests = np.zeros((n, full + (1 if tail else 0), digest_size(algorithm)),
                       dtype=np.uint8)
    if full:
        blocks = shards[:, :full * shard_size].reshape(n, full, shard_size)
        digests[:, :full] = hash_blocks_many(
            algorithm, blocks.reshape(n * full, shard_size)
        ).reshape(n, full, -1)
    if tail:
        digests[:, full] = hash_blocks_many(algorithm, shards[:, full * shard_size:])
    out = []
    for i in range(n):
        buf = bytearray()
        for b in range(full):
            buf += digests[i, b].tobytes()
            buf += shards[i, b * shard_size:(b + 1) * shard_size].tobytes()
        if tail:
            buf += digests[i, full].tobytes()
            buf += shards[i, full * shard_size:].tobytes()
        out.append(bytes(buf))
    return out


class BitrotError(Exception):
    """Stored digest does not match data (errFileCorrupt analogue)."""


class FramedShardReader:
    """Random-access verified reads from a bitrot-framed shard blob.

    The erasure decode path asks for whole shard blocks by index; every
    read re-hashes the block and compares against the stored digest
    (reference: streamingBitrotReader.ReadAt, cmd/bitrot-streaming.go:161-200).
    """

    def __init__(self, blob: bytes, shard_size: int, data_size: int,
                 algorithm: str = DEFAULT_ALGORITHM):
        self.blob = blob
        self.shard_size = shard_size
        self.data_size = data_size  # un-framed shard length
        self.algorithm = algorithm
        self.hsize = digest_size(algorithm)
        if algorithm == HIGHWAYHASH256S and \
                len(blob) != shard_file_size(data_size, shard_size, algorithm):
            raise BitrotError("framed shard file has wrong size")

    def block(self, index: int) -> np.ndarray:
        """Verified shard block `index` (uint8 array)."""
        start = index * self.shard_size
        if start >= self.data_size:
            raise BitrotError("block index out of range")
        blen = min(self.shard_size, self.data_size - start)
        off = index * (self.hsize + self.shard_size)
        want = self.blob[off:off + self.hsize]
        data = self.blob[off + self.hsize:off + self.hsize + blen]
        if len(want) < self.hsize or len(data) < blen:
            raise BitrotError("short framed shard read")
        if hash_block(self.algorithm, data) != bytes(want):
            raise BitrotError("bitrot detected")
        return np.frombuffer(data, dtype=np.uint8)


def verify_framed_shard(blob: bytes, shard_size: int, data_size: int,
                        algorithm: str = DEFAULT_ALGORITHM) -> None:
    """Full-file verification (reference: bitrotVerify, cmd/bitrot.go:164-215)."""
    r = FramedShardReader(blob, shard_size, data_size, algorithm)
    n_blocks = (data_size + shard_size - 1) // shard_size
    for i in range(n_blocks):
        r.block(i)


def read_framed_blocks_many(blobs, shard_size: int, data_size: int,
                            algorithm: str = DEFAULT_ALGORITHM,
                            device: bool = False):
    """Batched verified reads of same-shape framed shard blobs.

    blobs: sequence of bytes-like or None (a missing shard). Returns a
    list with, per blob, the verified un-framed data (uint8 [data_size])
    or None if the entry was None, malformed, or failed digest
    verification. This is the GET/heal hot path: instead of the
    reference's per-block ReadAt hashing (cmd/bitrot-streaming.go:
    161-200), ALL full blocks across all shards hash in one batch — on
    the TPU (ops/hh_device.framed_digests_device) when `device` is set
    and the batch is big enough, else in the vectorized lockstep host
    core. Ragged tail blocks hash per blob.
    """
    n_items = len(blobs)
    hsize = digest_size(algorithm)
    frame = hsize + shard_size
    nb = (data_size + shard_size - 1) // shard_size
    if nb == 0:
        return [np.zeros(0, dtype=np.uint8) if b is not None else None
                for b in blobs]
    tail = data_size - (nb - 1) * shard_size
    full = nb if tail == shard_size else nb - 1
    if tail == shard_size:
        tail = 0
    # Exact framed geometry for ANY algorithm (one digest per block) —
    # a truncated or padded blob must demote to a missing shard here,
    # never raise out of the batch.
    expect = full * frame + ((hsize + tail) if tail else 0)

    arrs: list = [None] * n_items
    for i, blob in enumerate(blobs):
        if blob is None or len(blob) != expect:
            continue
        arrs[i] = np.frombuffer(blob, dtype=np.uint8)
    oks = [i for i in range(n_items) if arrs[i] is not None]
    if not oks:
        return [None] * n_items

    bad = set()
    if full:
        wants = {i: arrs[i][:full * frame].reshape(full, frame)[:, :hsize]
                 for i in oks}
        blockv = {i: arrs[i][:full * frame].reshape(full, frame)[:, hsize:]
                  for i in oks}
        use_dev = (device and algorithm == HIGHWAYHASH256S
                   and frame % 4 == 0)
        got_dev = None
        if use_dev:
            from minio_tpu.ops import hh_device
            if hh_device.framed_digests_eligible(full * len(oks),
                                                 shard_size):
                u32 = [arrs[i][:full * frame].view(np.uint32)
                       .reshape(full, frame // 4) for i in oks]
                try:
                    got_dev = hh_device.framed_digests_device(u32) \
                        .reshape(len(oks), full, hsize)
                except Exception:  # noqa: BLE001 - device trouble is not
                    got_dev = None  # corruption; fall back to host hashing
        if got_dev is not None:
            for j, i in enumerate(oks):
                if not np.array_equal(got_dev[j], wants[i]):
                    bad.add(i)
        else:
            # One vectorized lockstep pass over ALL shards' full blocks.
            stacked = np.concatenate([blockv[i] for i in oks]) \
                if len(oks) > 1 else np.ascontiguousarray(blockv[oks[0]])
            got = hash_blocks_many(algorithm, stacked) \
                .reshape(len(oks), full, hsize)
            for j, i in enumerate(oks):
                if not np.array_equal(got[j], wants[i]):
                    bad.add(i)
    if tail:
        off = full * frame
        for i in oks:
            if i in bad:
                continue
            # Exact blob length was already enforced above, so the tail
            # frame is complete — only the digest can disagree.
            want = arrs[i][off:off + hsize].tobytes()
            data = arrs[i][off + hsize:off + hsize + tail]
            if hash_block(algorithm, data) != want:
                bad.add(i)

    out: list = [None] * n_items
    for i in oks:
        if i in bad:
            continue
        data = np.empty(data_size, dtype=np.uint8)
        if full:
            data[:full * shard_size].reshape(full, shard_size)[:] = \
                arrs[i][:full * frame].reshape(full, frame)[:, hsize:]
        if tail:
            off = full * frame
            data[full * shard_size:] = arrs[i][off + hsize:off + hsize + tail]
        out[i] = data
    return out


class SelfTestError(Exception):
    """A bitrot digest differs from the reference. Fatal at boot."""


def bitrot_self_test() -> None:
    """Reproduces the reference's boot-time golden check (cmd/bitrot.go:232-254).

    Schedule: starting from an empty message, repeat size*blocksize/size
    times: digest the message, append the digest to the message. The final
    digest must equal the golden value.
    """
    for algorithm, want_hex in _GOLDEN.items():
        size = digest_size(algorithm)
        rounds = _SELFTEST_BLOCKSIZE[algorithm]
        msg = b""
        sum_ = b""
        for _ in range(0, size * rounds, size):
            sum_ = hash_block(algorithm, msg)
            msg += sum_
        if sum_.hex() != want_hex:
            raise SelfTestError(
                f"bitrot self-test {algorithm}: got {sum_.hex()}, want {want_hex}")
