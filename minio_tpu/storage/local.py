"""Local drive backend: the per-drive POSIX storage engine.

The analogue of the reference's xlStorage (cmd/xl-storage.go): one
instance manages one drive (a directory tree), storing each object as

    <root>/<volume>/<object>/xl.meta          version journal (meta.py)
    <root>/<volume>/<object>/<dataDir>/part.N shard files (bitrot-framed)
    <root>/.mtpu.sys/tmp/<uuid>               staging for crash-safe commits

Writes land in tmp and are atomically renamed into place with fsync
(reference: CreateFile cmd/xl-storage.go:2092, RenameData :2557) so a
crash never exposes a partial object. Small shards inline into xl.meta
instead of separate files (reference threshold semantics,
internal/config/storageclass/storage-class.go:278).

This layer is deliberately synchronous & thread-safe per path; the
erasure object layer above fans out across drives with a thread pool the
way the reference fans out goroutines.
"""

from __future__ import annotations

import errno
import json
import os
import shutil
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Iterator, Optional

from minio_tpu.storage import meta as metafmt
from minio_tpu.storage.meta import (FileInfo, FileNotFoundErr, MetaError,
                                    VersionNotFoundErr, XLMeta)

SYS_VOL = ".mtpu.sys"
META_FILE = "xl.meta"
TMP_DIR = "tmp"
FORMAT_FILE = "format.json"
# Healing marker (the analogue of the reference's .healing.bin,
# cmd/background-newdisks-heal-ops.go): present on a drive that was
# re-formatted into its slot at runtime and has not finished its bulk
# heal. Holds the checkpointed HealingTracker JSON (object/drive_heal).
HEALING_FILE = "healing.json"

# Directory-entry fsync after rename commits. The reference syncs file
# CONTENTS (Fdatasync, cmd/xl-storage.go:2195) on every commit but syncs
# the parent directory only when MINIO_FS_OSYNC is set
# (cmd/common-main.go:745 defaults it off; cmd/xl-storage.go:1557
# globalSync) — on a journaling filesystem the rename itself orders with
# the journal, and a dir fsync per write costs more than the whole GF
# encode. Same default, same opt-in, here.
FS_OSYNC = os.environ.get("MTPU_FS_OSYNC", "").lower() in ("1", "on", "true")
# O_DIRECT for streaming shard writes (reference: disk.ODirectPlatform
# + globalAPIConfig.odirectEnabled, on by default where supported).
O_DIRECT_ENABLED = hasattr(os, "O_DIRECT") and \
    os.environ.get("MTPU_O_DIRECT", "on").lower() not in ("0", "off",
                                                          "false")


class StorageError(Exception):
    pass


class VolumeNotFound(StorageError):
    pass


class VolumeExists(StorageError):
    pass


class VolumeNotEmpty(StorageError):
    pass


class DiskAccessDenied(StorageError):
    pass


class FaultyDisk(StorageError):
    pass


class PowerFault(StorageError):
    """Base of injected power-cut faults (storage/crashdisk.PowerCut):
    a dead node's fault must propagate WHOLESALE out of commit_group —
    recording it as one member's error would let batch-mates proceed
    on a node that no longer exists."""


@dataclass
class DiskInfo:
    total: int = 0
    free: int = 0
    used: int = 0
    root_disk: bool = False
    healing: bool = False
    endpoint: str = ""
    disk_id: str = ""
    error: str = ""


@dataclass
class VolInfo:
    name: str
    created: int = 0


def _read_raw(path: str) -> bytes:
    """Whole-file read through raw os.open — the io.open stack costs
    several times the syscall for the small files the group-commit hot
    loop reads (version journals)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        size = os.fstat(fd).st_size
        buf = os.read(fd, size)
        while len(buf) < size:
            chunk = os.read(fd, size - len(buf))
            if not chunk:
                break
            buf += chunk
        return buf
    finally:
        os.close(fd)


def _write_raw(path: str, blob: bytes) -> None:
    """Whole-file write through raw os.open (no fsync — callers that
    need durability sync explicitly)."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        off = 0
        view = memoryview(blob)
        while off < len(blob):
            off += os.write(fd, view[off:])
    finally:
        os.close(fd)


def _is_valid_volname(vol: str) -> bool:
    return bool(vol) and vol not in (".", "..") and "/" not in vol and "\\" not in vol


class OfflineDisk:
    """Placeholder for a format position whose drive is missing/refused.

    Every operation fails with StorageError, which the erasure layer
    already tolerates up to parity (the reference models this as a nil
    StorageAPI slot in the set)."""

    def __init__(self, endpoint: str = "offline"):
        self.endpoint = endpoint

    def is_online(self) -> bool:
        return False

    def disk_id(self) -> str:
        return ""

    def read_format(self):
        return None

    def __getattr__(self, name: str):
        def fail(*a, **kw):
            raise StorageError(f"drive offline: {self.endpoint}")
        return fail


class LocalStorage:
    """One local drive. All paths are (volume, object-path) pairs."""

    def __init__(self, root: str, endpoint: str = ""):
        self.root = os.path.abspath(root)
        self.endpoint = endpoint or self.root
        self._disk_id: Optional[str] = None
        self._lock = threading.Lock()          # guards _path_locks
        self._path_locks: dict[str, threading.Lock] = {}
        # Group-commit WAL (commit_group): one append-mode file per
        # process, held open across batches; frames accumulate until a
        # checkpoint's sync truncates it (storage/group_commit).
        self._gc_mu = threading.Lock()
        self._gc_wal_fd: Optional[int] = None
        self._gc_wal_path = ""
        self._gc_dirty = 0                 # frames since last checkpoint
        import itertools
        self._gc_seq = itertools.count()   # tmp-name counter (hot loop)
        os.makedirs(os.path.join(self.root, SYS_VOL, TMP_DIR), exist_ok=True)

    def _path_lock(self, volume: str, path: str) -> threading.Lock:
        """Per-object lock serializing xl.meta read-modify-write cycles.

        Bounded: the map is pruned opportunistically (uncontended locks
        are dropped once the map grows past a soft cap)."""
        key = f"{volume}/{path}"
        with self._lock:
            lk = self._path_locks.get(key)
            if lk is None:
                if len(self._path_locks) > 4096:
                    for k in [k for k, v in self._path_locks.items()
                              if not v.locked()][:2048]:
                        del self._path_locks[k]
                lk = self._path_locks[key] = threading.Lock()
            return lk

    # ------------------------------------------------------------------
    # identity (format.json, reference: cmd/format-erasure.go)
    # ------------------------------------------------------------------

    def read_format(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.root, SYS_VOL, FORMAT_FILE), "rb") as f:
                return json.loads(f.read())
        except FileNotFoundError:
            return None

    def write_format(self, fmt: dict) -> None:
        blob = json.dumps(fmt, indent=2).encode()
        self._atomic_write(os.path.join(self.root, SYS_VOL, FORMAT_FILE), blob)
        self._disk_id = fmt.get("xl", {}).get("this")

    def disk_id(self) -> str:
        if self._disk_id is None:
            fmt = self.read_format()
            self._disk_id = fmt.get("xl", {}).get("this", "") if fmt else ""
        return self._disk_id or ""

    def is_online(self) -> bool:
        return os.path.isdir(os.path.join(self.root, SYS_VOL))

    # ------------------------------------------------------------------
    # path helpers
    # ------------------------------------------------------------------

    def _vol_dir(self, volume: str) -> str:
        if not _is_valid_volname(volume):
            raise StorageError(f"invalid volume name {volume!r}")
        return os.path.join(self.root, volume)

    def _obj_dir(self, volume: str, path: str) -> str:
        base = self._vol_dir(volume)
        full = os.path.normpath(os.path.join(base, path))
        if not full.startswith(base + os.sep) and full != base:
            raise DiskAccessDenied(path)  # path escape
        return full

    def _tmp_path(self) -> str:
        return os.path.join(self.root, SYS_VOL, TMP_DIR, str(uuid_mod.uuid4()))

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _atomic_write(self, dest: str, data: bytes) -> None:
        """tmp + fdatasync + rename: the crash-consistency primitive.

        Directories are created on demand (ENOENT retry) rather than
        with an unconditional makedirs pair — two mkdir walks per
        commit cost real time on the hot path, and a hot-replaced
        drive's missing staging tree is the rare case, not the common
        one."""
        tmp = self._tmp_path()
        try:
            f = open(tmp, "wb")
        except FileNotFoundError:
            os.makedirs(os.path.dirname(tmp), exist_ok=True)
            f = open(tmp, "wb")
        with f:
            f.write(data)
            f.flush()
            os.fdatasync(f.fileno())
        try:
            os.replace(tmp, dest)
        except FileNotFoundError:
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            os.replace(tmp, dest)
        if FS_OSYNC:
            self._fsync_dir(os.path.dirname(dest))

    # ------------------------------------------------------------------
    # volumes
    # ------------------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        d = self._vol_dir(volume)
        if os.path.isdir(d):
            raise VolumeExists(volume)
        os.makedirs(d)

    def make_vol_if_missing(self, volume: str) -> None:
        os.makedirs(self._vol_dir(volume), exist_ok=True)

    def list_vols(self) -> list[VolInfo]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name == SYS_VOL or not _is_valid_volname(name):
                continue
            st = os.stat(os.path.join(self.root, name))
            if os.path.isdir(os.path.join(self.root, name)):
                out.append(VolInfo(name=name, created=int(st.st_ctime_ns)))
        return out

    def stat_vol(self, volume: str) -> VolInfo:
        d = self._vol_dir(volume)
        if not os.path.isdir(d):
            raise VolumeNotFound(volume)
        return VolInfo(name=volume, created=int(os.stat(d).st_ctime_ns))

    def delete_vol(self, volume: str, force: bool = False) -> None:
        d = self._vol_dir(volume)
        if not os.path.isdir(d):
            raise VolumeNotFound(volume)
        if force:
            shutil.rmtree(d)
            return
        try:
            os.rmdir(d)
        except OSError as e:
            if e.errno in (errno.ENOTEMPTY, errno.EEXIST):
                raise VolumeNotEmpty(volume) from e
            raise

    # ------------------------------------------------------------------
    # raw file ops
    # ------------------------------------------------------------------

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._atomic_write(self._obj_dir(volume, path), data)

    def read_all(self, volume: str, path: str) -> bytes:
        try:
            with open(self._obj_dir(volume, path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{path}") from None
        except IsADirectoryError:
            raise FileNotFoundErr(f"{volume}/{path}") from None

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        full = self._obj_dir(volume, path)
        try:
            if recursive:
                shutil.rmtree(full)
            elif os.path.isdir(full):
                os.rmdir(full)
            else:
                os.remove(full)
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{path}") from None
        self._rm_empty_parents(os.path.dirname(full), self._vol_dir(volume))

    def _rm_empty_parents(self, d: str, stop: str) -> None:
        while d.startswith(stop + os.sep):
            try:
                os.rmdir(d)
            except OSError:
                return
            d = os.path.dirname(d)

    # ------------------------------------------------------------------
    # shard files (streaming writes land in tmp, commit via rename_data)
    # ------------------------------------------------------------------

    def create_file(self, volume: str, path: str, data: bytes | Iterator[bytes]) -> None:
        """Write a shard file with fdatasync (callers pass bitrot-framed
        bytes; reference: cmd/xl-storage.go:2195 Fdatasync).

        Large writes go O_DIRECT when the platform allows (reference:
        writeAllDirect + ioutil.CopyAligned, cmd/xl-storage.go:2147):
        shard data is written once and read rarely, so routing it
        around the page cache keeps streaming PUTs from evicting hot
        pages, and the post-write fdatasync becomes nearly free. The
        aligned bulk writes O_DIRECT; the ragged tail flips the flag
        off on the SAME fd (the CopyAligned trick); any O_DIRECT
        error falls back to the buffered path. MTPU_O_DIRECT=off
        disables it outright."""
        dest = self._obj_dir(volume, path)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if O_DIRECT_ENABLED and not isinstance(data, (bytes, bytearray,
                                                      memoryview)):
            # The iterator form is the streaming shard path — the one
            # worth O_DIRECT. Buffered fallback on any failure.
            if self._create_file_direct(dest, data):
                return
            # data may be partially consumed only when the FIRST open
            # failed (nothing written) — _create_file_direct guarantees
            # it; resume buffered with the same iterator.
        with open(dest, "wb") as f:
            if isinstance(data, (bytes, bytearray, memoryview)):
                f.write(data)
            else:
                for chunk in data:
                    f.write(chunk)
            f.flush()
            os.fdatasync(f.fileno())

    _ALIGN = 4096

    def _create_file_direct(self, dest: str, chunks) -> bool:
        """O_DIRECT streaming write; returns False (with NOTHING
        consumed or written) when O_DIRECT cannot be used here.

        The aligned staging buffer is LEASED from the buffer pool
        (io/bufpool) rather than mmap'd fresh per call — at steady
        state the shard-write path allocates nothing. The lease is
        acquired and released on this thread, so a deadline-abandoned
        health-wrapper call can never leave a recycled buffer exposed."""
        import fcntl

        from minio_tpu.io.bufpool import global_pool
        try:
            fd = os.open(dest, os.O_CREAT | os.O_WRONLY | os.O_TRUNC
                         | os.O_DIRECT, 0o644)
        except (OSError, AttributeError):
            return False
        align = self._ALIGN
        # Page-aligned staging buffer (O_DIRECT needs aligned memory;
        # pooled buffers are mmap pages, so any lease satisfies it).
        lease = global_pool().lease(1 << 20)
        buf = lease.raw
        fill = 0
        wrote_any = False

        def write_full(view):
            # os.pwritev-style full write: os.write may write SHORT
            # (e.g. ENOSPC mid-stream returns a count, not an error):
            # loop the remainder; zero progress raises rather than
            # silently truncating the shard.
            off = 0
            while off < view.nbytes:
                n = os.write(fd, view[off:])
                if n <= 0:
                    raise OSError(errno.EIO, "short write")
                off += n

        try:
            def drop_direct():
                fcntl.fcntl(fd, fcntl.F_SETFL,
                            fcntl.fcntl(fd, fcntl.F_GETFL)
                            & ~os.O_DIRECT)

            def flush_aligned():
                nonlocal fill, wrote_any
                whole = (fill // align) * align
                if whole:
                    write_full(memoryview(buf)[:whole])
                    wrote_any = True
                    rest = bytes(memoryview(buf)[whole:fill])
                    fill = len(rest)
                    buf.seek(0)
                    buf.write(rest)
                    buf.seek(0)

            for chunk in chunks:
                view = memoryview(chunk)
                while view.nbytes:
                    take = min(view.nbytes, len(buf) - fill)
                    buf[fill:fill + take] = view[:take]
                    fill += take
                    view = view[take:]
                    if fill == len(buf):
                        try:
                            flush_aligned()
                        except OSError:
                            if wrote_any:
                                raise
                            # First write rejected (FUSE/overlay mounts
                            # accept open(O_DIRECT) but EINVAL the
                            # write): everything consumed so far still
                            # sits in buf — drop the flag and continue
                            # buffered on the same fd.
                            drop_direct()
                            flush_aligned()
                            wrote_any = True
            try:
                flush_aligned()
            except OSError:
                if wrote_any:
                    raise
                drop_direct()
                flush_aligned()
                wrote_any = True
            if fill:
                # Ragged tail: drop O_DIRECT on the same fd and write
                # the remainder buffered (reference CopyAligned's
                # final unaligned write does the same).
                drop_direct()
                write_full(memoryview(buf)[:fill])
            os.fdatasync(fd)
            return True
        finally:
            os.close(fd)
            lease.release()

    # Bulk reads at/above this size go O_DIRECT (mirror of the write
    # path): GET/heal shard-window reads are read-once data that would
    # otherwise churn the page cache the hot PUT path needs.
    _DIRECT_READ_MIN = 1 << 20

    def read_file(self, volume: str, path: str, offset: int = 0,
                  length: int = -1) -> bytes:
        full = self._obj_dir(volume, path)
        try:
            if O_DIRECT_ENABLED:
                want = length
                if want < 0:
                    try:
                        want = max(0, os.path.getsize(full) - offset)
                    except OSError:
                        want = -1
                if want >= self._DIRECT_READ_MIN:
                    got = self._read_file_direct(full, offset, want)
                    if got is not None:
                        return got
            with open(full, "rb") as f:
                f.seek(offset)
                return f.read() if length < 0 else f.read(length)
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{path}") from None

    def _read_file_direct(self, full: str, offset: int,
                          length: int) -> Optional[bytes]:
        """O_DIRECT read of [offset, offset+length) via a page-aligned
        staging buffer (O_DIRECT demands aligned fd offset, memory and
        transfer size; mmap pages satisfy the memory part — the read
        counterpart of _create_file_direct's CopyAligned trick). None
        means "cannot here" (filesystem refused, e.g. tmpfs/overlay) —
        the caller falls back to the buffered path, nothing consumed.
        MTPU_O_DIRECT=off never reaches this."""
        from minio_tpu.io.bufpool import global_pool
        try:
            fd = os.open(full, os.O_RDONLY | os.O_DIRECT)
        except OSError:
            # Includes FileNotFoundError: the buffered path re-opens
            # and raises the proper not-found from its own attempt.
            return None
        align = self._ALIGN
        lo = (offset // align) * align
        head = offset - lo
        # Pooled aligned staging (lease scoped to this thread, so a
        # deadline-abandoned wrapper call cannot expose recycled
        # memory); os.preadv into it keeps the copy loop GIL-free.
        lease = global_pool().lease(1 << 20)
        buf = lease.raw
        out = bytearray()
        try:
            try:
                pos = lo
                need = head + length
                while need > 0:
                    take = min(len(buf),
                               (need + align - 1) // align * align)
                    n = os.preadv(fd, [memoryview(buf)[:take]], pos)
                    if n <= 0:
                        break                    # EOF
                    out += buf[:n]
                    pos += n
                    need -= n
            except OSError:
                # First read EINVAL (mount accepts open(O_DIRECT) but
                # rejects the read) or a mid-stream fault: either way
                # the buffered path re-reads from scratch.
                return None
            return bytes(out[head:head + length])
        finally:
            os.close(fd)
            lease.release()

    def stat_info_file(self, volume: str, path: str) -> os.stat_result:
        try:
            return os.stat(self._obj_dir(volume, path))
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{path}") from None

    # ------------------------------------------------------------------
    # versioned object metadata
    # ------------------------------------------------------------------

    def _meta_path(self, volume: str, path: str) -> str:
        return os.path.join(self._obj_dir(volume, path), META_FILE)

    def _read_meta(self, volume: str, path: str) -> XLMeta:
        try:
            with open(self._meta_path(volume, path), "rb") as f:
                return XLMeta.load(f.read())
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{path}") from None

    def _reclaim_data_dir(self, volume: str, path: str, data_dir: str) -> None:
        if data_dir:
            shutil.rmtree(os.path.join(self._obj_dir(volume, path), data_dir),
                          ignore_errors=True)

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        """Append/replace one version in the journal (creates it fresh)."""
        with self._path_lock(volume, path):
            try:
                xl = self._read_meta(volume, path)
            except FileNotFoundErr:
                xl = XLMeta()
            if xl.version_unchanged(fi):
                # Byte-identical re-add (hot-key overwrite-with-same-
                # content storms: MRF retries, heal rewrites of
                # agreeing copies): the journal would not change, so
                # skip the rewrite + fsync entirely.
                return
            old_ddir = xl.add_version(fi)
            self._atomic_write(self._meta_path(volume, path), xl.dump())
            self._reclaim_data_dir(volume, path, old_ddir)

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        with self._path_lock(volume, path):
            xl = self._read_meta(volume, path)
            if xl._find(fi.storage_version_id()) is None:
                raise VersionNotFoundErr(fi.version_id)
            if xl.version_unchanged(fi):
                return
            old_ddir = xl.add_version(fi)
            self._atomic_write(self._meta_path(volume, path), xl.dump())
            self._reclaim_data_dir(volume, path, old_ddir)

    def read_version(self, volume: str, path: str, version_id: str = "",
                     read_data: bool = False) -> FileInfo:
        xl = self._read_meta(volume, path)
        return xl.to_fileinfo(volume, path, version_id, read_data=read_data)

    def read_xl(self, volume: str, path: str) -> bytes:
        return self.read_all(volume, os.path.join(path, META_FILE))

    def list_versions(self, volume: str, path: str) -> list[FileInfo]:
        xl = self._read_meta(volume, path)
        return xl.list_versions(volume, path)

    def delete_version(self, volume: str, path: str, version_id: str = "",
                       force_del_marker: bool = False) -> None:
        """Remove one version; drops shard data when unreferenced; removes
        the whole object dir when the journal empties (reference:
        DeleteVersion, cmd/xl-storage.go)."""
        with self._path_lock(volume, path):
            xl = self._read_meta(volume, path)
            vid = version_id or metafmt.NULL_VERSION_ID
            v = xl._find(vid)
            if v is None:
                raise VersionNotFoundErr(version_id)
            data_dir = xl.delete_version(version_id)
            if data_dir and xl.shared_data_dir_count(vid, data_dir) == 0:
                self._reclaim_data_dir(volume, path, data_dir)
            if not xl.versions:
                self.delete(volume, path, recursive=True)
                return
            self._atomic_write(self._meta_path(volume, path), xl.dump())

    # ------------------------------------------------------------------
    # the commit protocol
    # ------------------------------------------------------------------

    def rename_data(self, src_volume: str, src_path: str, fi: FileInfo,
                    dst_volume: str, dst_path: str) -> None:
        """Atomically commit staged shard data + a new version.

        Staged layout (written by the erasure layer):
            <src>/<src_path>/<data_dir>/part.N
        Commit = move data dir into the object dir, then write the merged
        xl.meta (reference: RenameData, cmd/xl-storage.go:2557 — data
        moves first, metadata write is the commit point).
        """
        dst_dir = self._obj_dir(dst_volume, dst_path)
        with self._path_lock(dst_volume, dst_path):
            try:
                xl = self._read_meta(dst_volume, dst_path)
            except FileNotFoundErr:
                xl = XLMeta()
                fi.fresh = True
            if fi.data_dir:
                src_data = os.path.join(self._obj_dir(src_volume, src_path),
                                        fi.data_dir)
                dst_data = os.path.join(dst_dir, fi.data_dir)
                os.makedirs(dst_dir, exist_ok=True)
                if os.path.isdir(dst_data):
                    shutil.rmtree(dst_data)
                os.replace(src_data, dst_data)
            old_ddir = xl.add_version(fi)
            self._atomic_write(os.path.join(dst_dir, META_FILE), xl.dump())
            self._reclaim_data_dir(dst_volume, dst_path, old_ddir)
        # Clean the now-empty staging dir.
        shutil.rmtree(self._obj_dir(src_volume, src_path), ignore_errors=True)

    # ------------------------------------------------------------------
    # the GROUP commit protocol (storage/group_commit.py lanes)
    # ------------------------------------------------------------------

    def commit_group(self, ops: list, _info: Optional[dict] = None,
                     _hook=None) -> list:
        """Batched commit point for a group of write_metadata /
        rename_data ops (storage/group_commit.GroupOp). Returns a
        per-member result list: None = committed, Exception = that
        member failed — batch-mates are unaffected (isolation is per
        member for merge faults, per OBJECT for journal-write faults).

        Protocol (the group twin of _atomic_write — see the module
        docstring of storage/group_commit for the durability story):
          1. per rename_data member: staged data dir moves in;
          2. per DISTINCT object: one journal read-modify-write, every
             member merged in arrival order (same-object overwrite
             storms collapse to one rewrite; byte-identical re-adds
             skip entirely);
          3. ONE write-ahead record (gcommit/<wal>) holding every
             merged journal, fdatasync'd once — the batch's durability
             point, amortized across all members;
          4. per changed object: plain tmp + rename (no per-file
             fdatasync: a destination torn by a power cut is repaired
             from the WAL by replay_wals at mount);
          5. one _fsync_dir pass over distinct parents (MTPU_FS_OSYNC);
          6. old-data-dir reclaim + staging cleanup.
        WAL files retire at the next checkpoint (one os.sync every
        MTPU_GROUP_COMMIT_CKPT batches); replay is idempotent.

        `_info` (optional dict) receives batch accounting: objects,
        merged (same-object extra members), noops, fsyncs_saved.
        `_hook` is the crash-injection seam (storage/crashdisk): called
        at every durable sub-step boundary.
        """
        from minio_tpu.storage import group_commit as gc_mod
        results: list = [None] * len(ops)
        info = _info if _info is not None else {}
        info.setdefault("objects", 0)
        info.setdefault("merged", 0)
        info.setdefault("noops", 0)
        info.setdefault("fsyncs_saved", 0)
        groups: dict[tuple, list[int]] = {}
        order: list[tuple] = []
        for i, op in enumerate(ops):
            key = (op.volume, op.path)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(i)
        # All path locks, sorted: a fixed global order can never
        # deadlock against another multi-lock holder, and solo ops
        # take single locks (trivially compatible).
        lks = [self._path_lock(v, p) for (v, p) in sorted(groups)]
        for lk in lks:
            lk.acquire()
        staging_cleanup: list[tuple[str, str]] = []
        try:
            # (vol, path, meta_path, blob, member_idxs,
            #  replaced_ddirs, was_fresh)
            staged: list = []
            reclaims: list = []    # applied only once the journal LANDS
            for key in order:
                vol, path = key
                idxs = groups[key]
                dst_dir = self._obj_dir(vol, path)
                meta_path = dst_dir + os.sep + META_FILE
                # Raw os.open read: the io.open machinery costs ~4x the
                # syscall on this path, and at KV batch sizes the
                # per-member constant IS the commit cost.
                fresh = False
                try:
                    xl = XLMeta.load(_read_raw(meta_path))
                except (FileNotFoundError, NotADirectoryError):
                    xl = XLMeta()
                    fresh = True
                except (OSError, MetaError, ValueError) as e:
                    for i in idxs:
                        results[i] = e
                    continue
                if fresh:
                    # The object dir is needed for data-dir moves and
                    # the journal rename alike; one mkdir now beats an
                    # ENOENT retry dance per sub-step later.
                    try:
                        os.mkdir(dst_dir)
                    except FileExistsError:
                        pass
                    except FileNotFoundError:
                        os.makedirs(dst_dir, exist_ok=True)
                changed = False
                ok_idxs: list[int] = []
                obj_reclaims: list[str] = []
                for i in idxs:
                    op = ops[i]
                    # Snapshot so one member's fault cannot poison the
                    # merged journal its same-object mates commit.
                    snap = (list(xl.versions), dict(xl.inline))
                    try:
                        if op.kind == "rd":
                            if fresh:
                                op.fi.fresh = True
                            old = xl.add_version(op.fi)
                            if op.fi.data_dir:
                                if _hook is not None:
                                    _hook.step_move(op)
                                src_data = os.path.join(
                                    self._obj_dir(op.src_volume,
                                                  op.src_path),
                                    op.fi.data_dir)
                                dd = os.path.join(dst_dir,
                                                  op.fi.data_dir)
                                if os.path.isdir(dd):
                                    shutil.rmtree(dd)
                                os.replace(src_data, dd)
                            if old:
                                obj_reclaims.append(old)
                            staging_cleanup.append((op.src_volume,
                                                    op.src_path))
                            changed = True
                        else:
                            if xl.version_unchanged(op.fi):
                                info["noops"] += 1
                            else:
                                old = xl.add_version(op.fi)
                                if old:
                                    obj_reclaims.append(old)
                                changed = True
                        ok_idxs.append(i)
                    except PowerFault:
                        raise
                    except Exception as e:  # noqa: BLE001 - per member
                        xl.versions, xl.inline = snap
                        results[i] = e
                if ok_idxs:
                    info["objects"] += 1
                    info["merged"] += len(ok_idxs) - 1
                    if changed:
                        staged.append((vol, path, meta_path, xl.dump(),
                                       ok_idxs, obj_reclaims, fresh))
            if staged:
                recs = [(v, p, b) for v, p, _m, b, _, _, _ in staged]
                try:
                    self._gc_append_wal(recs, _hook)
                except PowerFault:
                    raise
                except Exception as e:  # noqa: BLE001 - batch durability
                    for _v, _p, _m, _b, idxs2, _r, _f in staged:
                        for i in idxs2:
                            if results[i] is None:
                                results[i] = e
                    staged = []
                # One WAL fdatasync covers what would have been one
                # fdatasync per changed journal on the solo path (plus
                # one dir fsync per commit under FS_OSYNC).
                info["fsyncs_saved"] += max(0, len(staged) - 1)
                tmp_dir = os.path.join(self.root, SYS_VOL, TMP_DIR)
                dirs: set[str] = set()
                for vol, path, meta_path, blob, idxs2, obj_reclaims, \
                        was_fresh in staged:
                    try:
                        prior = None
                        if _hook is not None:
                            _hook.step_rename(meta_path, blob)
                            prior = _hook.meta_prior(vol, path)
                        if was_fresh:
                            # FRESH object: no old journal a torn write
                            # could destroy, so the journal lands
                            # DIRECTLY (one filesystem-journal
                            # transaction instead of create+rename —
                            # the KV-ingest case is all fresh keys). A
                            # reader racing the µs-scale write sees an
                            # unparsable journal for a key that is not
                            # yet acked — the same "not there yet" it
                            # would have seen a µs earlier; a power cut
                            # leaves a torn dest replay_wals repairs.
                            _write_raw(meta_path, blob)
                        else:
                            # Overwrite: tmp + rename, so the OLD
                            # journal stays intact (and visible) until
                            # the atomic replace.
                            tmp = os.path.join(
                                tmp_dir, f"gc{os.getpid()}-"
                                f"{next(self._gc_seq)}")
                            _write_raw(tmp, blob)
                            os.replace(tmp, meta_path)
                        dirs.add(meta_path.rsplit(os.sep, 1)[0])
                        if _hook is not None:
                            _hook.note_rename(meta_path, blob, prior)
                        # Old data dirs reclaim only once the NEW
                        # journal actually landed — a failed rename
                        # leaves the old journal, whose versions still
                        # reference them.
                        reclaims.extend((vol, path, dd)
                                        for dd in obj_reclaims)
                    except PowerFault:
                        raise
                    except Exception as e:  # noqa: BLE001 - per object
                        for i in idxs2:
                            if results[i] is None:
                                results[i] = e
                if FS_OSYNC:
                    for d in sorted(dirs):
                        self._fsync_dir(d)
            for vol, path, ddir in reclaims:
                self._reclaim_data_dir(vol, path, ddir)
        finally:
            for lk in lks:
                lk.release()
        for sv, sp in staging_cleanup:
            shutil.rmtree(self._obj_dir(sv, sp), ignore_errors=True)
        return results

    # When False (set by crash doubles that own durability timing) the
    # background checkpoint coordinator never touches this drive's
    # WAL — checkpoints happen only through an explicit, hook-ticked
    # gc_checkpoint().
    _gc_auto = True

    def _gc_append_wal(self, recs: list, _hook=None) -> None:
        """Append one batch frame to this drive's group-commit WAL and
        fdatasync it — the batch's durability point. The file is
        created once and held open; checkpoints truncate it in place
        (no per-batch create/unlink, see storage/group_commit)."""
        from minio_tpu.storage import group_commit as gc_mod
        frame = gc_mod.encode_frame(recs)
        with self._gc_mu:
            created = False
            if self._gc_wal_fd is None:
                path = gc_mod.wal_file_path(self.root)
                flags = os.O_WRONLY | os.O_CREAT | os.O_APPEND
                try:
                    fd = os.open(path, flags, 0o644)
                except FileNotFoundError:
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    fd = os.open(path, flags, 0o644)
                self._gc_wal_fd = fd
                self._gc_wal_path = path
                created = True
            if _hook is not None:
                _hook.step_wal(self._gc_wal_path, frame)
            fd = self._gc_wal_fd
            off = 0
            view = memoryview(frame)
            while off < len(frame):
                off += os.write(fd, view[off:])
            os.fdatasync(fd)
            if created:
                if FS_OSYNC:
                    self._fsync_dir(os.path.dirname(self._gc_wal_path))
                if _hook is not None:
                    _hook.note_wal(self._gc_wal_path,
                                   synced_dir=FS_OSYNC)
            self._gc_dirty += 1
        if self._gc_auto and _hook is None:
            from minio_tpu.storage.group_commit import \
                schedule_checkpoint
            schedule_checkpoint(self)

    def gc_pending(self) -> int:
        """Frames appended since the last checkpoint."""
        with self._gc_mu:
            return self._gc_dirty

    def gc_truncate_wal(self, expect: Optional[int] = None) -> int:
        """Drop the WAL's frames (caller has ALREADY made the renamed
        destinations durable via sync); returns the frame count.
        `expect` guards the sync-to-truncate window: a frame appended
        AFTER the caller's sync was not covered by it, so a changed
        count skips the truncate (those frames retire next
        checkpoint) instead of erasing a live durability point."""
        with self._gc_mu:
            n = self._gc_dirty
            if n == 0 or (expect is not None and n != expect):
                return 0
            if self._gc_wal_fd is not None:
                try:
                    os.ftruncate(self._gc_wal_fd, 0)
                except OSError:
                    pass
            self._gc_dirty = 0
        return n

    def gc_checkpoint(self, _hook=None) -> int:
        """Forced checkpoint: make every renamed group-commit
        destination durable (one os.sync) and truncate the WAL frames
        it was protecting. Returns the number of frames retired.
        Called at set close (graceful stops leave no frames for the
        next boot to replay) and by the crash harness through its
        injection hook."""
        pre = self.gc_pending()
        if not pre:
            return 0
        if _hook is not None:
            _hook.step_sync()
        os.sync()
        return self.gc_truncate_wal(expect=pre)

    def gc_close(self) -> None:
        """Close the WAL fd (after a final checkpoint; the empty file
        itself may remain — replay of an empty WAL is a no-op)."""
        with self._gc_mu:
            fd, self._gc_wal_fd = self._gc_wal_fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        """Atomic same-drive move (multipart assembly, commit plumbing)."""
        src = self._obj_dir(src_volume, src_path)
        dst = self._obj_dir(dst_volume, dst_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            raise FileNotFoundErr(f"{src_volume}/{src_path}") from None

    # ------------------------------------------------------------------
    # listing / walking
    # ------------------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str, count: int = -1) -> list[str]:
        """Entries of one directory level: files as-is, dirs with '/'."""
        base = self._obj_dir(volume, dir_path) if dir_path else self._vol_dir(volume)
        try:
            names = sorted(os.listdir(base))
        except FileNotFoundError:
            raise FileNotFoundErr(f"{volume}/{dir_path}") from None
        out = []
        for n in names:
            if os.path.isdir(os.path.join(base, n)):
                out.append(n + "/")
            else:
                out.append(n)
            if 0 < count <= len(out):
                break
        return out

    def walk_dir(self, volume: str, base_dir: str = "",
                 recursive: bool = True,
                 forward_from: str = "") -> Iterator[tuple[str, bytes]]:
        """Yield (object_path, raw xl.meta) sorted, streaming.

        The per-drive listing primitive (reference: WalkDir,
        cmd/metacache-walk.go:73): depth-first sorted recursion; a
        directory containing xl.meta IS an object and is yielded instead
        of being descended into (objects can nest under object names).
        """
        vol = self._vol_dir(volume)
        if not os.path.isdir(vol):
            raise VolumeNotFound(volume)

        # emit() keeps only the MOST RECENT journal blob so the descend
        # event for the same directory can derive data dirs without a
        # second read+parse — single slot by construction; an unbounded
        # map would grow O(num_objects x journal_size) over a long walk.
        # A miss in data_dirs_of simply re-reads the file.
        last_blob: list = [None, b""]  # [rel, blob]

        def emit(rel: str) -> Optional[tuple[str, bytes]]:
            try:
                with open(os.path.join(vol, rel, META_FILE), "rb") as f:
                    blob = f.read()
                last_blob[0], last_blob[1] = rel, blob
                return rel, blob
            except (FileNotFoundError, NotADirectoryError):
                return None

        def is_uuid(n: str) -> bool:
            try:
                uuid_mod.UUID(n)
                return True
            except ValueError:
                return False

        def data_dirs_of(rel: str) -> frozenset[str]:
            """Data-dir names referenced by rel's journal — ONLY those are
            version data, any other UUID-named child is a legitimate user
            key prefix and must be walked."""
            try:
                blob = last_blob[1] if last_blob[0] == rel else None
                if blob is None:
                    with open(os.path.join(vol, rel, META_FILE), "rb") as f:
                        blob = f.read()
                xl = XLMeta.load(blob)
                return frozenset(v.get("ddir", "") for v in xl.versions
                                 if v.get("ddir"))
            except (OSError, MetaError):
                # Unreadable journal: no children get classified as data
                # dirs, so every UUID child is walked as a possible key
                # (harmless — dirs without xl.meta yield nothing).
                return frozenset()

        def walk(rel: str, rel_is_obj: bool) -> Iterator[tuple[str, bytes]]:
            """Yields in GLOBAL lexicographic key order. A directory `d`
            produces two ordered events: the object key "d" (sorts before
            siblings like "d-x") and the subtree "d/" (sorts after them) —
            interleaving siblings between an object and its nested keys,
            exactly as S3 key order requires. When rel is itself an
            object, children matching its journal's data dirs are shard
            storage, not keys (any other UUID-named child IS a key)."""
            full = os.path.join(vol, rel) if rel else vol
            try:
                names = os.listdir(full)
            except (FileNotFoundError, NotADirectoryError):
                return
            ddirs: Optional[frozenset] = None  # lazily parsed journal
            events = []  # (sort_key, name, kind)
            for n in names:
                if n == META_FILE:
                    continue
                if rel_is_obj and is_uuid(n):
                    if ddirs is None:
                        ddirs = data_dirs_of(rel)
                    if n in ddirs:
                        continue  # version data dir, not a key prefix
                if os.path.isdir(os.path.join(full, n)):
                    events.append((n, n, "obj"))
                    events.append((n + "/", n, "descend"))
            events.sort()
            for sort_key, n, kind in events:
                child = f"{rel}/{n}" if rel else n
                if kind == "obj":
                    if child >= forward_from or forward_from.startswith(child):
                        got = emit(child)
                        if got is not None:
                            yield got
                else:
                    subtree = child + "/"
                    # Prune subtrees wholly before the resume point.
                    if subtree < forward_from and \
                            not forward_from.startswith(subtree):
                        continue
                    if recursive:
                        is_obj = os.path.exists(
                            os.path.join(vol, child, META_FILE))
                        yield from walk(child, is_obj)
                    else:
                        yield subtree, b""

        base_is_obj = bool(base_dir) and os.path.exists(
            os.path.join(vol, base_dir, META_FILE))
        yield from walk(base_dir, base_is_obj)

    # ------------------------------------------------------------------
    # scanning walk (metadata plane: batched native journal decode)
    # ------------------------------------------------------------------

    def walk_scan(self, volume: str, base_dir: str = "",
                  forward_from: str = "", shallow: bool = False):
        """The listing walk's per-drive primitive: like walk_dir, but
        journals are read in pooled-lease batches and decoded by ONE
        GIL-free native scan per batch (storage/meta_scan) instead of
        one msgpack unpack per object. Yields, in global key order:

            (path, vlist, None)   summarized object (trimmed entry)
            (path, vlist, blob)   summarized, but a version's metadata
                                  exceeds the summary — blob rides
                                  along for full-fidelity resolution
            (path, None, blob)    scanner rejected the journal; the
                                  caller runs the XLMeta.load path
            (path + "/", PREFIX_MARK, None)   shallow mode only: a key
                                  prefix with evidence of keys below it

        `shallow=True` walks ONE directory level under base_dir and
        emits subtree markers instead of descending — the delimiter
        ("/") listing shape: a browse page costs O(page), not
        O(subtree). Marker evidence is one probe scandir per child
        subtree (first grandchild with a journal or a directory), so a
        directory chain holding no keys at all may surface a transient
        empty prefix — dirs are pruned on delete, and the reference's
        non-recursive WalkDir accepts the same ambiguity.

        Unlike walk_dir, this walk never parses journals to classify
        data dirs: it descends everywhere, and a version data dir
        (part files only, never a journal or a subdirectory) simply
        yields nothing. Nested keys shadowed by a same-named data dir
        are therefore listed here — strictly more visible, never less.
        """
        vol = self._vol_dir(volume)
        if not os.path.isdir(vol):
            raise VolumeNotFound(volume)
        from minio_tpu.storage.meta_scan import BlobScanner
        scanner = BlobScanner()
        try:
            if shallow:
                yield from self._walk_shallow(vol, base_dir, forward_from)
                return

            def rec(rel):
                full = os.path.join(vol, rel) if rel else vol
                try:
                    with os.scandir(full) as it:
                        dirs = sorted(
                            e.name for e in it
                            if e.is_dir(follow_symlinks=False))
                except (FileNotFoundError, NotADirectoryError):
                    return
                events = []
                for n in dirs:
                    events.append((n, n, True))
                    events.append((n + "/", n, False))
                events.sort()
                for _, n, obj_slot in events:
                    child = f"{rel}/{n}" if rel else n
                    if obj_slot:
                        if not (child >= forward_from
                                or forward_from.startswith(child)):
                            continue
                        try:
                            fd = os.open(os.path.join(full, n, META_FILE),
                                         os.O_RDONLY)
                        except OSError:
                            continue    # not an object (or vanished)
                        try:
                            if scanner.full():
                                yield from scanner.flush()
                            scanner.add(child, fd)
                        finally:
                            os.close(fd)
                    else:
                        subtree = child + "/"
                        if subtree < forward_from and \
                                not forward_from.startswith(subtree):
                            continue
                        yield from rec(child)

            yield from rec(base_dir)
            yield from scanner.flush()
        finally:
            scanner.close()

    def _walk_shallow(self, vol: str, base_dir: str, forward_from: str):
        """One level under base_dir: objects at this level plus subtree
        markers (see walk_scan). Unbatched — shallow pages are small
        and each child's journal feeds both its entry and its marker
        decision."""
        from minio_tpu.storage.meta_scan import (PREFIX_MARK, scan_blob,
                                                 summary_sufficient)
        full = os.path.join(vol, base_dir) if base_dir else vol
        try:
            with os.scandir(full) as it:
                dirs = sorted(e.name for e in it
                              if e.is_dir(follow_symlinks=False))
        except (FileNotFoundError, NotADirectoryError):
            return
        events = []
        for n in dirs:
            events.append((n, n, True))
            events.append((n + "/", n, False))
        events.sort()
        probes: dict[str, list] = {}    # child -> its subdir names

        def probe(n: str) -> list:
            if n in probes:
                return probes.pop(n)
            try:
                with os.scandir(os.path.join(full, n)) as it:
                    sub = sorted(e.name for e in it
                                 if e.is_dir(follow_symlinks=False))
            except OSError:
                sub = []
            return sub

        def has_keys_below(n: str, subdirs: list) -> bool:
            # Evidence probe: a grandchild holding a journal (a key) or
            # any directory (a deeper tree). Stops at first evidence.
            for s in subdirs:
                try:
                    with os.scandir(os.path.join(full, n, s)) as it:
                        for e in it:
                            if e.name == META_FILE or \
                                    e.is_dir(follow_symlinks=False):
                                return True
                except OSError:
                    continue
            return False

        for _, n, obj_slot in events:
            child = f"{base_dir}/{n}" if base_dir else n
            if obj_slot:
                if not (child >= forward_from
                        or forward_from.startswith(child)):
                    continue
                sub = probe(n)
                if len(probes) < 128:
                    probes[n] = sub
                try:
                    with open(os.path.join(full, n, META_FILE),
                              "rb") as f:
                        blob = f.read()
                except OSError:
                    continue
                vlist = scan_blob(blob)
                need_blob = vlist is None or not summary_sufficient(vlist)
                yield child, vlist, (blob if need_blob else None)
            else:
                subtree = child + "/"
                if subtree < forward_from and \
                        not forward_from.startswith(subtree):
                    continue
                if has_keys_below(n, probe(n)):
                    yield subtree, PREFIX_MARK, None

    # ------------------------------------------------------------------
    # health / usage
    # ------------------------------------------------------------------

    def disk_info(self) -> DiskInfo:
        st = os.statvfs(self.root)
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        healing = os.path.exists(
            os.path.join(self.root, SYS_VOL, HEALING_FILE))
        return DiskInfo(total=total, free=free, used=total - free,
                        healing=healing, endpoint=self.endpoint,
                        disk_id=self.disk_id())


# -- healing marker (drive replacement lifecycle) -----------------------
# Duck-typed over the StorageAPI (read_all/write_all/delete) so the
# same helpers work on LocalStorage, RemoteStorage and health-wrapped
# drives. The tracker JSON itself is owned by object/drive_heal.


def read_healing(disk) -> Optional[dict]:
    """The drive's healing tracker, or None (absent / unreachable)."""
    try:
        return json.loads(disk.read_all(SYS_VOL, HEALING_FILE))
    except Exception:  # noqa: BLE001 - no marker == not healing
        return None


def write_healing(disk, tracker: dict) -> None:
    disk.write_all(SYS_VOL, HEALING_FILE,
                   json.dumps(tracker, indent=1).encode())


def clear_healing(disk) -> None:
    try:
        disk.delete(SYS_VOL, HEALING_FILE)
    except Exception:  # noqa: BLE001 - already gone / offline
        pass


# Graceful-stop stamp: present only when the previous process exited
# through its shutdown path. Its ABSENCE at boot means a crash/power
# cut, which is what gates the (O(namespace)) deep recovery sweep —
# clean restarts skip straight to the cheap tmp/staging purge. The
# failure direction is safe: a lost stamp only costs an extra sweep.
CLEAN_SHUTDOWN_FILE = "clean.shutdown"


def mark_clean_shutdown(disk) -> None:
    root = getattr(disk, "root", None)
    if root is None:
        return
    try:
        with open(os.path.join(root, SYS_VOL, CLEAN_SHUTDOWN_FILE),
                  "wb") as f:
            f.write(b"1")
    except OSError:
        pass


def consume_clean_shutdown(disk) -> bool:
    """True when the previous stop was graceful. Consumes the stamp so
    the next boot re-evaluates from scratch."""
    root = getattr(disk, "root", None)
    if root is None:
        return False
    try:
        os.remove(os.path.join(root, SYS_VOL, CLEAN_SHUTDOWN_FILE))
        return True
    except OSError:
        return False


def _staging_owner_pid(name: str) -> Optional[int]:
    """Pid embedded in a pid-tagged staging/tmp entry name
    (erasure_object.new_staging writes `p<pid>-<uuid>`)."""
    if not name.startswith("p"):
        return None
    head = name[1:].split("-", 1)[0]
    return int(head) if head.isdigit() else None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True        # EPERM: exists, owned by someone else


def sweep_stale_tmp(disk, min_age: Optional[float] = None) -> int:
    """Boot-time janitor: remove crash leftovers under the system
    volume's tmp/ and staging/ dirs (the reference sweeps .minio.sys/tmp
    at startup; without this, every crashed PUT's staged shards
    accumulate forever). Returns the number of entries removed.

    Safety gates (a worker-0 sweep runs while sibling pre-forked
    workers may already be serving):
      * pid-tagged staging entries (`p<pid>-<uuid>`, see
        erasure_object.new_staging) belonging to a LIVE process other
        than this one are skipped — they are a sibling's in-flight
        PUT; a tag whose owner is dead is a crash leftover at any age;
      * untagged entries are age-gated by `min_age` (default
        MTPU_SWEEP_MIN_AGE, seconds): a freshly-modified legacy entry
        survives the sweep.
    """
    root = getattr(disk, "root", None)
    if root is None:
        return 0
    if min_age is None:
        try:
            min_age = float(os.environ.get("MTPU_SWEEP_MIN_AGE", "0"))
        except ValueError:
            min_age = 0.0
    now = time.time()
    me = os.getpid()
    removed = 0
    for sub in (TMP_DIR, "staging"):
        base = os.path.join(root, SYS_VOL, sub)
        try:
            entries = os.listdir(base)
        except (FileNotFoundError, NotADirectoryError):
            continue
        for name in entries:
            full = os.path.join(base, name)
            pid = _staging_owner_pid(name)
            if pid is not None:
                # Pid tag is authoritative: a live sibling's entry is
                # untouchable at any age; a dead owner's entry is a
                # crash leftover at any age.
                if pid != me and _pid_alive(pid):
                    continue
            elif min_age > 0:
                try:
                    if now - os.lstat(full).st_mtime < min_age:
                        continue
                except OSError:
                    continue
            try:
                if os.path.isdir(full):
                    shutil.rmtree(full)
                else:
                    os.unlink(full)
                removed += 1
            except OSError:
                continue
    return removed


def _is_uuid_name(n: str) -> bool:
    try:
        uuid_mod.UUID(n)
        return True
    except ValueError:
        return False


def _only_part_files(d: str) -> bool:
    """True when `d` holds nothing but shard part files — the shape of
    a version data dir, never of a user key prefix."""
    try:
        names = os.listdir(d)
    except OSError:
        return False
    return bool(names) and all(
        n.startswith("part.") and os.path.isfile(os.path.join(d, n))
        for n in names)


def recovery_sweep(disk, min_age: Optional[float] = None) -> dict:
    """Mount-time crash recovery (extends sweep_stale_tmp): after a
    power cut, bring this drive back to a state where every object is
    either the complete old or the complete new version.

      1. stale tmp/staging purge (torn in-flight writes live there —
         the tmp+fdatasync+rename protocol never exposes a torn file
         at its destination);
      2. dangling data-dir repair: a UUID-named, part-files-only child
         that no xl.meta version references is the first half of an
         interrupted rename_data commit — the journal (= the commit
         point) never flipped, so the orphan is removed and the old
         version stands;
      3. a corrupt (torn) xl.meta is quarantined and the object is
         reported for heal — peers hold the quorum copy;
      4. an xl.meta version whose data dir is MISSING (a lost,
         un-fsynced directory entry) is reported for heal so the MRF
         can rebuild the shards from peers.

    Returns {"removed": int, "dangling": int, "heal": [(bucket, path)]}
    — the caller enqueues the heal list onto the owning set's MRF.
    Only safe before the drive starts serving.

    Group-commit WALs replay FIRST (storage/group_commit.replay_wals):
    a batched commit's journal claims must be reinstated before the
    dangling-data-dir scan looks, or the scan would reap data dirs the
    replayed journals reference.
    """
    from minio_tpu.storage.group_commit import replay_wals
    gc = replay_wals(disk)
    out = {"removed": sweep_stale_tmp(disk, min_age),
           "dangling": 0, "heal": [],
           "wal_replayed": gc["replayed"],
           "wal_repaired": gc["repaired"]}
    root = getattr(disk, "root", None)
    if root is None:
        return out

    def scan(vol: str, rel: str) -> None:
        base = os.path.join(root, vol, rel) if rel else os.path.join(root,
                                                                     vol)
        meta_path = os.path.join(base, META_FILE)
        refs: Optional[frozenset] = None
        if os.path.isfile(meta_path):
            try:
                with open(meta_path, "rb") as f:
                    xl = XLMeta.load(f.read())
                refs = frozenset(v.get("ddir", "") for v in xl.versions
                                 if v.get("ddir"))
                # A version whose shard data should exist locally but
                # does not (lost directory entry): rebuildable from
                # peers. Delete markers carry no ddir; inline versions
                # live in the journal itself; tier-transitioned
                # versions reclaimed their local data on purpose.
                if any(v.get("ddir") and not v.get("inline")
                       and not (v.get("meta") or {}).get(
                           "x-internal-tier-name")  # tier.META_TIER
                       and not os.path.isdir(os.path.join(base, v["ddir"]))
                       for v in xl.versions):
                    out["heal"].append((vol, rel))
            except (OSError, MetaError):
                # Torn journal: quarantine — an unreadable commit point
                # serves nothing; heal rewrites it from the quorum.
                try:
                    os.remove(meta_path)
                except OSError:
                    pass
                out["heal"].append((vol, rel))
                refs = frozenset()
        try:
            names = os.listdir(base)
        except OSError:
            return
        for n in names:
            if n == META_FILE:
                continue
            full = os.path.join(base, n)
            if not os.path.isdir(full):
                continue
            child = f"{rel}/{n}" if rel else n
            if _is_uuid_name(n) and _only_part_files(full) \
                    and (refs is None or n not in refs):
                # Data dir without a journal claim: the un-committed
                # half of an interrupted rename_data. Remove; the old
                # version (or nothing, for a fresh PUT) stands.
                shutil.rmtree(full, ignore_errors=True)
                out["dangling"] += 1
                continue
            scan(vol, child)
        try:
            if not os.listdir(base) and rel:
                os.rmdir(base)
        except OSError:
            pass

    try:
        vols = sorted(os.listdir(root))
    except OSError:
        return out
    for vol in vols:
        if vol == SYS_VOL or not _is_valid_volname(vol):
            continue
        if os.path.isdir(os.path.join(root, vol)):
            scan(vol, "")
    return out
