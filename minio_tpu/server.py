"""`python -m minio_tpu.server` — boot a single-node S3 server.

The analogue of the reference's serverMain (cmd/server-main.go:746):
run the boot self-tests (hard-fail on wrong math, like the reference's
erasure/bitrot self-tests at :799-803), build the erasure set over the
drive paths, and serve the S3 API.

Usage:
    python -m minio_tpu.server --address 127.0.0.1:9000 /data/d1 /data/d2 ...

Credentials come from MTPU_ROOT_USER / MTPU_ROOT_PASSWORD
(default minioadmin/minioadmin).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="minio_tpu.server")
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--parity", type=int, default=None,
                    help="EC parity shards (default: by drive count)")
    ap.add_argument("--ec-backend", choices=["auto", "host", "tpu"],
                    default="auto",
                    help="where the GF(2^8) math runs (tpu = JAX device)")
    ap.add_argument("drives", nargs="+", help="local drive directories")
    args = ap.parse_args(argv)

    if args.parity is not None and not 0 <= args.parity <= len(args.drives) // 2:
        ap.error(f"--parity must be in [0, {len(args.drives) // 2}] "
                 f"for {len(args.drives)} drives")

    # Boot self-tests: identical math to the reference or refuse to serve.
    from minio_tpu.erasure.selftest import erasure_self_test
    from minio_tpu.storage.bitrot import bitrot_self_test
    erasure_self_test()
    bitrot_self_test()

    backend = None
    if args.ec_backend == "tpu":
        from minio_tpu.ops.rs_device import DeviceBackend
        backend = DeviceBackend()
    elif args.ec_backend == "auto":
        try:
            import jax
            if jax.default_backend() == "tpu":
                from minio_tpu.ops.rs_device import DeviceBackend
                backend = DeviceBackend()
        except Exception:  # noqa: BLE001 - no JAX device -> host math
            backend = None

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.local import LocalStorage

    disks = [LocalStorage(p) for p in args.drives]
    layer = ErasureSet(disks, parity=args.parity, backend=backend)
    srv = S3Server(layer, address=args.address)
    print(f"minio-tpu serving S3 on {srv.address} "
          f"({len(disks)} drives, parity={layer.default_parity}, "
          f"ec-backend={'tpu' if backend else 'host'})", flush=True)
    srv.start()
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
