"""`python -m minio_tpu.server` — boot a single-node S3 server.

The analogue of the reference's serverMain (cmd/server-main.go:746):
run the boot self-tests (hard-fail on wrong math, like the reference's
erasure/bitrot self-tests at :799-803), build the erasure set over the
drive paths, and serve the S3 API.

Usage:
    python -m minio_tpu.server --address 127.0.0.1:9000 /data/d1 /data/d2 ...

Credentials come from MTPU_ROOT_USER / MTPU_ROOT_PASSWORD
(default minioadmin/minioadmin).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="minio_tpu.server")
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--parity", type=int, default=None,
                    help="EC parity shards (default: by drive count)")
    ap.add_argument("--ec-backend", choices=["auto", "host", "tpu"],
                    default="auto",
                    help="where the GF(2^8) math runs (tpu = JAX device)")
    ap.add_argument("--set-size", type=int, default=None,
                    help="drives per erasure set (default: auto 2-16)")
    ap.add_argument("drives", nargs="+",
                    help="drive dirs; `{1...N}` ellipses expand, and each "
                         "ellipses argument forms its own server pool")
    args = ap.parse_args(argv)

    # Boot self-tests: identical math to the reference or refuse to serve.
    from minio_tpu.erasure.selftest import erasure_self_test
    from minio_tpu.storage.bitrot import bitrot_self_test
    erasure_self_test()
    bitrot_self_test()

    backend = None
    if args.ec_backend == "tpu":
        from minio_tpu.ops.rs_device import DeviceBackend
        backend = DeviceBackend()
    elif args.ec_backend == "auto":
        try:
            import jax
            if jax.default_backend() == "tpu":
                from minio_tpu.ops.rs_device import DeviceBackend
                backend = DeviceBackend()
        except Exception:  # noqa: BLE001 - no JAX device -> host math
            backend = None

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.local import LocalStorage, OfflineDisk
    from minio_tpu.topology import ellipses, format as fmt_mod

    try:
        pool_specs = ellipses.parse_pools(args.drives)
    except ValueError as e:
        ap.error(str(e))
    pools = []
    deployment_id = None
    n_sets = n_drives = 0
    for spec in pool_specs:
        disks = [LocalStorage(p) for p in spec]
        try:
            set_size = args.set_size or ellipses.choose_set_size(len(disks))
        except ValueError as e:
            ap.error(str(e))
        if len(disks) % set_size:
            ap.error(f"{len(disks)} drives not divisible into sets "
                     f"of {set_size}")
        if args.parity is not None and not 0 <= args.parity <= set_size // 2:
            ap.error(f"--parity must be in [0, {set_size // 2}] for "
                     f"{set_size}-drive sets")
        try:
            ordered, fmt = fmt_mod.boot(disks, set_size, deployment_id)
        except fmt_mod.FormatError as e:
            print(f"FATAL: format verification failed: {e}", file=sys.stderr)
            return 1
        if deployment_id is not None and fmt.deployment_id != deployment_id:
            # Two unrelated deployments must never be federated
            # (reference: mixed deployment ids are a fatal boot error).
            print(f"FATAL: pool {len(pools)} belongs to deployment "
                  f"{fmt.deployment_id}, expected {deployment_id}",
                  file=sys.stderr)
            return 1
        deployment_id = deployment_id or fmt.deployment_id
        ordered = [d if d is not None else OfflineDisk(f"pos-{i}")
                   for i, d in enumerate(ordered)]
        sets = [ErasureSet(ordered[i:i + set_size], parity=args.parity,
                           backend=backend)
                for i in range(0, len(ordered), set_size)]
        pools.append(ErasureSets(sets, fmt.deployment_id))
        n_sets += len(sets)
        n_drives += len(ordered)
    layer = ServerPools(pools)
    srv = S3Server(layer, address=args.address)
    print(f"minio-tpu serving S3 on {srv.address} "
          f"({len(pools)} pools, {n_sets} sets, {n_drives} drives, "
          f"ec-backend={'tpu' if backend else 'host'})", flush=True)
    srv.start()
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
