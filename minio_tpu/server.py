"""`python -m minio_tpu.server` — boot a (possibly distributed) S3 server.

The analogue of the reference's serverMain (cmd/server-main.go:746):
run the boot self-tests (hard-fail on wrong math, like the reference's
erasure/bitrot self-tests at :799-803), bring up the grid mesh when the
topology spans nodes (initGlobalGrid, :882-889), quorum-verify
format.json, build pools/sets over local + remote drives, and serve the
S3 API.

Usage (single node):
    python -m minio_tpu.server --address 127.0.0.1:9000 /data/d{1...4}

Distributed (run the SAME command on every node; endpoints owned by
other nodes are reached over the grid on port+1000):
    python -m minio_tpu.server --address 127.0.0.1:9001 \\
        http://127.0.0.1:9001/data/n1/d{1...2} \\
        http://127.0.0.1:9002/data/n2/d{1...2}

Credentials come from MTPU_ROOT_USER / MTPU_ROOT_PASSWORD
(default minioadmin/minioadmin).
"""

from __future__ import annotations

import argparse
import os
import socket as socket_mod
import sys
import time

GRID_PORT_OFFSET = 1000


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="minio_tpu.server")
    ap.add_argument("--address", default="0.0.0.0:9000")
    ap.add_argument("--parity", type=int, default=None,
                    help="EC parity shards (default: by drive count)")
    ap.add_argument("--ec-backend", choices=["auto", "host", "tpu"],
                    default="auto",
                    help="where the GF(2^8) math runs (tpu = JAX device)")
    ap.add_argument("--set-size", type=int, default=None,
                    help="drives per erasure set (default: auto 2-16)")
    ap.add_argument("--boot-timeout", type=float, default=120.0,
                    help="seconds to wait for peer nodes at boot")
    ap.add_argument("--scanner-interval", type=float, default=60.0,
                    help="seconds between background scanner cycles "
                         "(0 disables the background thread)")
    ap.add_argument("--drive-timeout", type=float, default=10.0,
                    help="per-op drive deadline in seconds; a drive "
                         "tripping it repeatedly is circuit-broken "
                         "(0 disables the health wrapper)")
    ap.add_argument("--notify-webhook", default="",
                    help="webhook endpoint URL for bucket event "
                         "notifications (target id 'webhook')")
    ap.add_argument("--notify-mqtt", default="",
                    help="host:port/topic of an MQTT 3.1.1 broker for "
                         "event notifications (target id 'mqtt')")
    ap.add_argument("--notify-nats", default="",
                    help="host:port/subject of a NATS server for event "
                         "notifications (target id 'nats')")
    ap.add_argument("--notify-redis", default="",
                    help="host:port/listkey of a Redis server for event "
                         "notifications (target id 'redis')")
    ap.add_argument("--audit-webhook", default="",
                    help="webhook endpoint URL receiving one audit "
                         "record per completed request")
    ap.add_argument("--compression", action="store_true",
                    help="transparently compress eligible objects "
                         "(text-like extensions/content types)")
    ap.add_argument("--ftp-address", default="",
                    help="also serve the namespace over FTP at "
                         "host:port (reference: --ftp)")
    ap.add_argument("drives", nargs="+",
                    help="drive dirs or http://host:port/path endpoints; "
                         "`{1...N}` ellipses expand, and each ellipses "
                         "argument forms its own server pool")
    args = ap.parse_args(argv)

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.server import Credentials, S3Server
    from minio_tpu.storage.local import LocalStorage, OfflineDisk
    from minio_tpu.storage.remote import RemoteStorage, StorageRPCService
    from minio_tpu.topology import ellipses, format as fmt_mod

    my_host, _, my_port_s = args.address.rpartition(":")
    my_host = my_host or "0.0.0.0"
    my_port = int(my_port_s)
    local_hosts = {"127.0.0.1", "localhost", "0.0.0.0", my_host,
                   socket_mod.gethostname()}

    def is_local(ep: ellipses.Endpoint) -> bool:
        return ep.host is None or (ep.port == my_port
                                   and ep.host in local_hosts)

    try:
        pool_specs = ellipses.parse_pools(args.drives)
        pool_eps = [[ellipses.parse_endpoint(s) for s in spec]
                    for spec in pool_specs]
    except ValueError as e:
        ap.error(str(e))

    all_eps = [ep for spec in pool_eps for ep in spec]
    remote_nodes = sorted({(ep.host, ep.port) for ep in all_eps
                           if not is_local(ep)})
    distributed = bool(remote_nodes)

    # Argument validation that must fail in THIS process, before any
    # worker fork: a bad flag erroring only inside a forked child
    # would leave a supervising parent waiting on nothing.
    for spec in pool_eps:
        try:
            ss = args.set_size or ellipses.choose_set_size(len(spec))
        except ValueError as e:
            ap.error(str(e))
        if len(spec) % ss:
            ap.error(f"{len(spec)} drives not divisible into sets "
                     f"of {ss}")
        if args.parity is not None and not 0 <= args.parity <= ss // 2:
            ap.error(f"--parity must be in [0, {ss // 2}] for "
                     f"{ss}-drive sets")

    # Pre-forked SO_REUSEPORT front-end (io/workers.py): N worker
    # processes each run this whole boot (MTPU_HTTP_WORKERS=1 in the
    # children prevents recursion). MUST run before self-tests and
    # ec-backend detection: those may import and initialize JAX, and
    # forking a process with a live XLA runtime (its thread pools, a
    # claimed TPU device) is undefined — every child does its own
    # detection instead. Default = cores. Distributed topologies
    # pre-fork too (N nodes x M workers): the node's SINGLE grid port
    # is owned by worker 0, and sibling workers reach the node's lock
    # authority / coherence singleton over loopback — see the
    # worker-topology wiring below.
    from minio_tpu.io import workers as workers_mod
    worker_id = os.environ.get("MTPU_WORKER_ID", "")
    if not worker_id:
        n_workers = workers_mod.worker_count_from_env()
        if n_workers > 1:
            return workers_mod.serve_cli(
                list(argv) if argv is not None else sys.argv[1:],
                args.address, n_workers, main)
    # Worker identity: "" = plain single-process boot; "0" = the
    # pre-forked worker that owns node-singleton duties (grid listener,
    # lock authority, coherence, recovery sweeps); "1".."M-1" = sibling
    # workers. MTPU_WORKER_TOTAL is the fleet width M (1 outside worker
    # mode) — background ownership shards over node_count x M slots.
    is_w0 = worker_id in ("", "0")
    try:
        worker_total = max(1, int(os.environ.get("MTPU_WORKER_TOTAL",
                                                 "") or "1"))
    except ValueError:
        worker_total = 1

    # Boot self-tests: identical math to the reference or refuse to serve.
    from minio_tpu.erasure.selftest import erasure_self_test
    from minio_tpu.storage.bitrot import bitrot_self_test
    erasure_self_test()
    bitrot_self_test()

    backend = None
    if args.ec_backend == "tpu":
        from minio_tpu.ops.rs_device import DeviceBackend
        backend = DeviceBackend()
    elif args.ec_backend == "auto":
        try:
            import jax
            if jax.default_backend() == "tpu":
                from minio_tpu.ops.rs_device import DeviceBackend
                backend = DeviceBackend()
        except Exception as e:  # noqa: BLE001 - no JAX device -> host math
            print(f"ec-backend auto-detect: no TPU ({type(e).__name__}: {e}); "
                  "using host GF kernels", file=sys.stderr)
            backend = None
    if backend is not None:
        # Boot gate for the DEVICE kernels too: the golden-vector sweep
        # with the host cutover disabled, so the Pallas/XLA GF path that
        # large PUTs will actually run is what gets verified (the plain
        # erasure_self_test above covers the host core only — its
        # 256-byte vectors are all below HOST_CUTOVER_BYTES).
        from minio_tpu.ops.rs_device import DeviceBackend
        erasure_self_test(DeviceBackend(host_cutover=0))

    # -- grid mesh up BEFORE the object layer (reference: initGlobalGrid
    #    precedes newObjectLayer, cmd/server-main.go:882-942) ----------
    local_disks: dict[str, LocalStorage] = {}
    for ep in all_eps:
        if is_local(ep):
            local_disks[ep.path] = LocalStorage(
                ep.path, endpoint=str(ep) if ep.is_url else "")

    grid_srv = None
    lockers = []
    if distributed:
        from minio_tpu.grid import GridServer, client_for
        from minio_tpu.grid.dsync import (DistNSLock, LocalLocker,
                                          LockServer, RemoteLocker)
        grid_port = my_port + GRID_PORT_OFFSET
        if is_w0:
            grid_srv = GridServer(grid_port)
            StorageRPCService(local_disks).register_into(grid_srv)
            lock_server = LockServer()
            lock_server.register_into(grid_srv)
            node_info = {"deployment_id": ""}
            grid_srv.register("node.info", lambda p: dict(node_info))
            grid_srv.start()
            print(f"grid mesh on :{grid_srv.port} "
                  f"({len(local_disks)} local drives)", flush=True)

            # Wait for every peer's grid before touching formats (the
            # reference's bootstrap handshake,
            # cmd/bootstrap-peer-server.go).
            deadline = time.monotonic() + args.boot_timeout
            for host, port in remote_nodes:
                c = client_for(host, port + GRID_PORT_OFFSET)
                while not c.ping(timeout=2.0):
                    if time.monotonic() > deadline:
                        print(f"WARN: peer {host}:{port} unreachable; "
                              f"its drives boot offline", file=sys.stderr)
                        break
                    time.sleep(0.5)

            lockers = [LocalLocker(lock_server)] + [
                RemoteLocker(client_for(h, p + GRID_PORT_OFFSET))
                for h, p in remote_nodes]
        else:
            # Sibling worker on an N x M node: worker 0 owns the node's
            # grid plane, so this process binds nothing — the node's own
            # lock vote is one more RemoteLocker, over loopback. Worker
            # 0 booted first (the pool forks siblings only after it
            # accepts), so the wait below only spins across a worker-0
            # respawn window; an unreachable loopback then degrades to
            # quorum fast-fails (503s) until it returns, never a wedge.
            self_client = client_for("127.0.0.1", grid_port)
            deadline = time.monotonic() + args.boot_timeout
            while not self_client.ping(timeout=2.0):
                if time.monotonic() > deadline:
                    print("WARN: node grid plane (worker 0) unreachable; "
                          "lock quorum degraded", file=sys.stderr)
                    break
                time.sleep(0.2)
            lockers = [RemoteLocker(self_client)] + [
                RemoteLocker(client_for(h, p + GRID_PORT_OFFSET))
                for h, p in remote_nodes]

    def make_disk(ep: ellipses.Endpoint):
        if is_local(ep):
            return local_disks[ep.path]
        return RemoteStorage(ep.host, ep.port + GRID_PORT_OFFSET, ep.path)

    # -- format boot + object layer ------------------------------------
    pools = []
    deployment_id = None
    n_sets = n_drives = 0
    # (pool_idx, bucket, path) found damaged by the mount-time recovery
    # sweep — enqueued onto the owning set's MRF once sets exist.
    pending_heals: list[tuple] = []
    for spec in pool_eps:
        disks = [make_disk(ep) for ep in spec]
        # Set-size/divisibility/parity were validated pre-fork above
        # (they must error in the parent, not inside a worker child);
        # this recomputation cannot fail.
        set_size = args.set_size or ellipses.choose_set_size(len(disks))

        # Only the node owning the pool's first endpoint initializes a
        # fresh format; everyone else waits for it to appear (reference:
        # prepare-storage leader init + waitForFormatErasure).
        if distributed and not is_local(spec[0]):
            deadline = time.monotonic() + args.boot_timeout
            while all(fmt_mod._safe_read(d) is None for d in disks):
                if time.monotonic() > deadline:
                    break
                time.sleep(0.5)
        attempts = 5 if distributed else 1
        ordered = fmt = None
        for attempt in range(attempts):
            try:
                ordered, fmt = fmt_mod.boot(disks, set_size, deployment_id)
                break
            except fmt_mod.FormatError as e:
                # Distributed boot race: the leader may still be writing
                # formats; retry before declaring the layout broken.
                if attempt == attempts - 1:
                    print(f"FATAL: format verification failed: {e}",
                          file=sys.stderr)
                    return 1
                time.sleep(2.0)
        if deployment_id is not None and fmt.deployment_id != deployment_id:
            # Two unrelated deployments must never be federated
            # (reference: mixed deployment ids are a fatal boot error).
            print(f"FATAL: pool {len(pools)} belongs to deployment "
                  f"{fmt.deployment_id}, expected {deployment_id}",
                  file=sys.stderr)
            return 1
        deployment_id = deployment_id or fmt.deployment_id
        ordered = [d if d is not None else OfflineDisk(f"pos-{i}")
                   for i, d in enumerate(ordered)]
        # Boot janitor + crash recovery: crashed PUTs leave staged
        # shards under the system volume and interrupted rename_data
        # commits leave dangling data dirs / journals referencing lost
        # data (reference sweeps .minio.sys/tmp at startup). The
        # recovery sweep purges the former, removes the latter's
        # orphans, and reports journal-vs-data mismatches for MRF
        # repair. First-boot worker 0 only: siblings (and a RESPAWNED
        # worker 0) boot while others are already serving — pid-tagged
        # staging names and the age gate add a second line of defense
        # (storage/local.sweep_stale_tmp). MTPU_RECOVERY_SWEEP=off
        # falls back to the plain tmp/staging purge.
        if is_w0 and not os.environ.get("MTPU_WORKER_RESPAWN"):
            from minio_tpu.storage.local import (consume_clean_shutdown,
                                                 recovery_sweep,
                                                 sweep_stale_tmp)
            deep_sweep = os.environ.get(
                "MTPU_RECOVERY_SWEEP", "on").lower() not in ("0", "off",
                                                             "false")
            for d in ordered:
                try:
                    # The deep sweep walks the whole namespace — only
                    # worth it when the previous stop was NOT graceful
                    # (crash/power cut). Clean restarts take the cheap
                    # tmp/staging purge.
                    if deep_sweep and not consume_clean_shutdown(d):
                        rep = recovery_sweep(d)
                        for vol, path in rep["heal"]:
                            pending_heals.append((len(pools), vol, path))
                    else:
                        # Clean restart: still replay any group-commit
                        # WALs a SIGKILLed worker left behind (cheap
                        # no-op when gcommit/ is empty).
                        from minio_tpu.storage.group_commit import \
                            replay_wals
                        replay_wals(d)
                        sweep_stale_tmp(d)
                except Exception:  # noqa: BLE001 - janitor never blocks boot
                    pass
        # Deadline + circuit-breaker wrapper: a hung (not dead) drive
        # fails fast instead of stalling every quorum fan-out
        # (reference: cmd/xl-storage-disk-id-check.go).
        if args.drive_timeout > 0:
            from minio_tpu.storage.health import wrap_disks
            ordered = wrap_disks(ordered, op_timeout=args.drive_timeout)
        sets = [ErasureSet(ordered[i:i + set_size], parity=args.parity,
                           backend=backend)
                for i in range(0, len(ordered), set_size)]
        if distributed:
            from minio_tpu.grid.dsync import DistNSLock
            for s in sets:
                s.ns = DistNSLock(lockers)
        pools.append(ErasureSets(sets, fmt.deployment_id))
        n_sets += len(sets)
        n_drives += len(ordered)

    if distributed and grid_srv is not None:
        node_info["deployment_id"] = deployment_id
        # Cross-node config handshake: peers must agree on deployment
        # (reference: verifyServerSystemConfig, cmd/server-main.go:928).
        # Worker 0 only — it owns the node's grid identity; siblings
        # booted after it already verified.
        from minio_tpu.grid import client_for as _cf
        for host, port in remote_nodes:
            try:
                info = _cf(host, port + GRID_PORT_OFFSET).call(
                    "node.info", None, timeout=3.0)
                peer_dep = info.get("deployment_id", "")
                if peer_dep and peer_dep != deployment_id:
                    print(f"FATAL: peer {host}:{port} deployment "
                          f"{peer_dep} != {deployment_id}", file=sys.stderr)
                    return 1
            except Exception:  # noqa: BLE001 - peer still booting
                pass

    layer = ServerPools(pools)
    if distributed:
        # Coordinator election for fleet-wide migrations: rebalance and
        # decommission take a dsync write lease (decom.coordinator_lease)
        # over the same lockers as the namespace locks, so exactly one
        # node drives a walk and a SIGKILLed coordinator's lease expires
        # after MTPU_GRID_LOCK_TTL for any peer to take over.
        layer.lockers = lockers
    # Resume an interrupted pool decommission from its checkpoint
    # (reference: pools.Init resuming persisted decom state; with the
    # lease above, at most one booting node actually wins the resume).
    if len(pools) > 1:
        try:
            if layer.resume_decommission() is not None:
                print("resuming interrupted pool decommission",
                      flush=True)
        except Exception as e:  # noqa: BLE001 - decom must not block boot
            print(f"WARN: decommission resume failed: {e}",
                  file=sys.stderr)
        # Likewise an interrupted rebalance (reference: pools.Init
        # loading persisted rebalanceMeta).
        try:
            if layer.resume_rebalance() is not None:
                print("resuming interrupted pool rebalance", flush=True)
        except Exception as e:  # noqa: BLE001 - must not block boot
            print(f"WARN: rebalance resume failed: {e}", file=sys.stderr)
    # Crash-recovery repairs found by the mount-time sweep: route each
    # damaged object to its owning set's MRF (heals are idempotent and
    # deep-verified there).
    for pool_idx, vol, path in pending_heals:
        try:
            p = pools[pool_idx]
            p.sets[p.set_index(path)].mrf.enqueue(vol, path)
        except Exception:  # noqa: BLE001 - scanner converges it later
            pass
    # Background data scanner: usage accounting, 1/1024 deep-heal
    # sampling, replaced-drive format restore (reference:
    # cmd/data-scanner.go's scanner loop).
    from minio_tpu.object.scanner import Scanner
    all_sets = [s for p in pools for s in p.sets]
    # Fleet-sharded background ownership (N nodes x M workers): every
    # erasure set is owned by exactly ONE (node, worker) slot, so each
    # cycle covers each set once FLEET-wide — distributed nodes used to
    # scan/heal every set on every node (N x duplication), and worker
    # mode parked all of it on worker 0 while siblings idled. Node
    # ranks come from the sorted endpoint topology, identical on every
    # node by construction (the same server command runs everywhere);
    # a dead slot's sets go unscanned only until its worker respawns.
    widx = int(worker_id or 0)
    if distributed:
        _nodes = sorted({(ep.host, ep.port) for ep in all_eps})
        _remote = set(remote_nodes)
        node_rank = next((i for i, hp in enumerate(_nodes)
                          if hp not in _remote), 0)
        fleet_slots = len(_nodes) * worker_total
        bg_slot = node_rank * worker_total + widx
    else:
        fleet_slots = worker_total
        bg_slot = widx
    owned_sets = [s for i, s in enumerate(all_sets)
                  if i % fleet_slots == bg_slot]
    scanner = Scanner(owned_sets, interval=args.scanner_interval)
    # ILM: lifecycle rules stored per bucket evaluate on every scanned
    # object (reference: cmd/bucket-lifecycle.go via the scanner).
    from minio_tpu.object.lifecycle import make_scanner_hook

    def _ilm_deleted(es, bucket, key, deleted):
        # Late binding: this hook is wired before the replication
        # engine boots.  ILM-created delete markers replicate like API
        # deletes — expiry on the source must not strand a live latest
        # on the target.
        del es
        try:
            r = srv.replicator
        except NameError:
            return
        if r is not None and hasattr(r, "ilm_deleted"):
            r.ilm_deleted(bucket, key, deleted)

    scanner.on_object.append(make_scanner_hook(on_delete=_ilm_deleted))
    # A slot with no owned sets (more slots than sets) starts nothing;
    # the single-process single-node boot degenerates to slot 0 of 1
    # owning everything — exactly the old behavior.
    if args.scanner_interval > 0 and owned_sets:
        scanner.start()
    layer.scanner = scanner
    # Drive lifecycle manager: detect hot-replaced (fresh) drives while
    # serving, restore their slot format, and run checkpointed bulk
    # heals that resume across restarts (object/drive_heal). Sharded
    # over the same ownership slots as the scanner — format restore and
    # healing markers ride the generic disk interface, so an owner
    # converges another node's replaced drive over the grid.
    from minio_tpu.object.drive_heal import (DriveHealManager,
                                             admission_pressure)
    drive_heal = DriveHealManager(
        owned_sets, total_hint=lambda: scanner.usage.objects)
    layer.drive_heal = drive_heal
    if owned_sets:
        drive_heal.start(interval=args.scanner_interval
                         if args.scanner_interval > 0 else 10.0)
    # IAM: users/service-accounts/policies, replicated on pool 0's
    # drives (reference: cmd/iam.go bootstrap).
    from minio_tpu.iam import IAMSys
    creds = Credentials()
    creds.iam = IAMSys(pools[0].sets, creds.access_key, creds.secret_key)
    srv = S3Server(layer, address=args.address, credentials=creds)
    # Quota enforcement reads the scanner's usage accounting.
    srv.scanner = scanner
    # Drive-heal progress in admin heal status + Prometheus; the bulk
    # heal sheds while admission control reports client queueing.
    srv.drive_heal = drive_heal
    drive_heal.pressure = lambda: admission_pressure(srv.admission)
    # Migration walks (rebalance/decommission) are a background class
    # too: they pause while foreground requests queue, same signal as
    # the bulk heal above (object/decom.MigrationGovernor).
    layer.migration_pressure = lambda: admission_pressure(srv.admission)
    # Warm tiers: registry on pool 0's drives, resolved by every set's
    # read/transition paths (reference: globalTierConfigMgr).
    from minio_tpu.object.tier import TierRegistry
    srv.tiers = TierRegistry(pools[0].sets)
    for s in all_sets:
        s.tiers = srv.tiers
    # Site replication: re-arm a persisted peer registry
    # (reference: site replication config survives restarts).
    from minio_tpu.replication.site import (SiteReplicator,
                                            hook_iam_changes, load_config)
    site_cfg = load_config(pools[0].sets)
    if site_cfg:
        srv.site = SiteReplicator(layer, pools[0].sets, site_cfg,
                                  iam=creds.iam)
        print(f"site replication armed "
              f"({len(site_cfg.get('peers', []))} peers)", flush=True)
    hook_iam_changes(srv)
    # Batch jobs: resume any that a crash or restart interrupted
    # (reference: batch jobs survive restarts via their checkpoints).
    from minio_tpu.object.batch import BatchJobs
    srv.batch = BatchJobs(layer, pools[0].sets)
    srv.batch.kms = srv.kms
    if is_w0:
        # Checkpointed batch jobs resume once, not once per worker.
        try:
            resumed = srv.batch.resume_all()
            if resumed:
                print(f"resumed {resumed} interrupted batch job(s)",
                      flush=True)
        except Exception as e:  # noqa: BLE001 - batch must not block boot
            print(f"WARN: batch resume failed: {e}", file=sys.stderr)
    srv.compression = args.compression
    # Persisted config overrides flags (the flags seed first boot).
    from minio_tpu.s3 import config as cfg_mod
    try:
        cfg_mod.apply_config(srv, cfg_mod.load_config(layer))
    except Exception:  # noqa: BLE001 - config is optional
        pass
    if distributed:
        # Self-declared node identity: unique per node, stable across
        # restarts. The bind address is neither when every node runs
        # the default 0.0.0.0:9000 — fall back to the hostname, which
        # is what distinguishes nodes in a same-port deployment. Every
        # worker carries it: slow-op records, trace spans, and the
        # federated telemetry snapshots are labeled with the node that
        # produced them.
        from minio_tpu.utils import tracing as tracing_mod
        ident_host = my_host if my_host not in ("0.0.0.0", "::", "") \
            else socket_mod.gethostname()
        node_id = f"{ident_host}:{my_port}"
        srv.node_id = node_id
        tracing_mod.set_node(node_id)
        # Peer control plane: mutations of shared state (IAM, config,
        # decom) fan out an immediate cache invalidation to every
        # peer; the per-cache TTL covers unreachable peers
        # (reference: cmd/notification.go + cmd/peer-rest-client.go:304).
        from minio_tpu.grid.peers import (PeerNotifier, RELOAD_HANDLER,
                                          make_reload_handler)
        peer_notifier = PeerNotifier(
            [client_for(h, p + GRID_PORT_OFFSET) for h, p in remote_nodes])
        if grid_srv is not None:
            # Inbound reload pings land on the node's grid listener —
            # worker 0's process. Sibling workers converge through
            # their per-cache TTLs, the same backstop that covers an
            # unreachable peer.
            grid_srv.register(RELOAD_HANDLER, make_reload_handler(
                iam=creds.iam, object_layer=layer,
                apply_config=lambda: cfg_mod.apply_config(
                    srv, cfg_mod.load_config(layer))))
        srv.peer_notify = peer_notifier.broadcast
        srv.peer_notifier = peer_notifier
        creds.iam.on_change = lambda: peer_notifier.broadcast("iam")
        layer.on_decom_change = lambda: peer_notifier.broadcast("decom")
        # Namespace + bucket-meta invalidation rides the GENERATION
        # protocol (grid/coherence): acked-or-escalated pushes, and a
        # reconnecting peer must resync generations before its caches
        # re-arm — the contract that lets fi_cache and the listing
        # caches stay ON cluster-wide.
        from minio_tpu.grid.coherence import (CLASS_BUCKET_META,
                                              CLASS_LISTING, FileGate,
                                              PeerCoherence, RELAY_HANDLER,
                                              make_set_invalidator)
        all_sets_d = [s for p in pools for s in p.sets]
        # N x M worker topology: the gate state file and relay-failure
        # flag live in the same shared dir io/workers.py keeps its
        # bump-generation files in (worker mode only — a plain
        # single-process node needs neither).
        shared_dir = None
        if worker_id:
            _root = workers_mod._first_drive_root(layer)
            if _root is not None:
                shared_dir = os.path.join(_root, ".mtpu.sys", "workers")
                os.makedirs(shared_dir, exist_ok=True)
        if grid_srv is not None:
            # Coherence reuses the node identity above (peers key
            # applied-generation records by it; restart detection rides
            # the instance id).
            coherence = PeerCoherence(
                node_id=node_id,
                peers={f"{h}:{p}": client_for(h, p + GRID_PORT_OFFSET)
                       for h, p in remote_nodes},
                on_invalidate=make_set_invalidator(all_sets_d,
                                                   layer=layer))
            coherence.register_into(grid_srv)
            if shared_dir is not None:
                coherence.state_path = os.path.join(
                    shared_dir, "coherence.state")
                coherence.relay_flag_path = os.path.join(
                    shared_dir, "coherence.relay-flag")
            layer.on_bucket_meta_change = \
                lambda bucket: coherence.broadcast(bucket,
                                                   CLASS_BUCKET_META)
            # A write on this node orphans peers' walk streams +
            # fileinfo entries for the bucket (leading-edge coalesced
            # inside MetaCache.bump, trailing-guaranteed).
            for s in all_sets_d:
                s.metacache.on_bump = (
                    lambda bucket: coherence.broadcast(bucket,
                                                       CLASS_LISTING))
                # Synchronous acked pushes: a timer-deferred
                # invalidation would be a cross-node staleness window
                # no gate covers.
                s.metacache.bump_coalesce = 0.0
                # EVERY set gates on coherence in distributed mode — a
                # set whose drives are all local here is remote from
                # the peers' side, so peers mutate it too.
                s.fi_cache.remote_gate = coherence.coherent
                s.metacache.remote_gate = coherence.coherent
            coherence.start()
            srv.coherence = coherence
        else:
            # Sibling worker: worker 0 owns the node's PeerCoherence.
            # Outbound bumps relay to it over loopback (it bumps the
            # node generation and fans out to peers); a failed relay
            # leaves the dead-man flag its next sync tick converts into
            # a wildcard broadcast, so a mutation can never vanish into
            # a worker-0 respawn window. Inbound peer invalidations
            # reach this process through the shared list.gen/meta.gen
            # files the wrapped bump funnel already maintains. The
            # cache gate is worker 0's published state file — stale
            # heartbeat reads as incoherent (fail closed).
            relay_client = client_for("127.0.0.1",
                                      my_port + GRID_PORT_OFFSET)
            _flag = os.path.join(shared_dir, "coherence.relay-flag") \
                if shared_dir is not None else None

            def _relay(bucket, cls):
                try:
                    relay_client.call(RELAY_HANDLER,
                                      {"b": bucket, "c": cls},
                                      timeout=5.0)
                except Exception:  # noqa: BLE001 - dead-man flag below
                    if _flag is not None:
                        try:
                            with open(_flag, "w"):
                                pass
                        except OSError:
                            pass
            gate = FileGate(os.path.join(shared_dir, "coherence.state")) \
                if shared_dir is not None else (lambda: False)
            layer.on_bucket_meta_change = \
                lambda bucket: _relay(bucket, CLASS_BUCKET_META)
            for s in all_sets_d:
                s.metacache.on_bump = (
                    lambda bucket: _relay(bucket, CLASS_LISTING))
                s.metacache.bump_coalesce = 0.0
                s.fi_cache.remote_gate = gate
                s.metacache.remote_gate = gate
        # Cluster-wide profiling fan-out (reference: profiling rides
        # NotificationSys too). Inbound verbs live on the node's grid
        # listener (worker 0); outbound peer clients on every worker.
        if grid_srv is not None:
            from minio_tpu.s3.profiling import (PROFILE_HANDLER,
                                                make_profile_handler)
            grid_srv.register(PROFILE_HANDLER,
                              make_profile_handler(srv.profiler))
            # Per-node admin-info summaries for the cluster info fan-out.
            from minio_tpu.s3.metrics import node_info as _node_info
            grid_srv.register("peer.info",
                              lambda payload: _node_info(srv))
            # Fleet-federated telemetry: peers pull this node's merged
            # metrics snapshot (all its workers) in one call, and tail
            # its live trace entries as a stream (?cluster=true admin
            # trace). Both land on worker 0, which holds the node's
            # control plane and merges siblings through it.
            from minio_tpu.s3.metrics import \
                peer_metrics_state as _peer_metrics_state
            from minio_tpu.s3.trace import make_trace_stream
            grid_srv.register("peer.metrics",
                              lambda payload: _peer_metrics_state(srv))
            grid_srv.register_stream("trace.stream",
                                     make_trace_stream(srv))
        srv.profile_peers = [
            (f"{h}:{p}", client_for(h, p + GRID_PORT_OFFSET))
            for h, p in remote_nodes]
        # Any-node elastic admin verbs: status fans IN (the coordinator
        # holds counters fresher than the persisted checkpoint), stop
        # fans OUT (it must reach whichever node drives the walk).
        def _elastic_status(payload):
            rb = getattr(layer, "_rebalance", None)
            dc = layer._decom
            return {
                "rebalance": layer.rebalance_status(),
                "rebalance_live": bool(rb is not None
                                       and not rb.wait(timeout=0)),
                "decommission": layer.decommission_status(),
                "decommission_live": bool(dc is not None
                                          and not dc.wait(timeout=0)),
            }

        def _elastic_stop(payload):
            kind = (payload or {}).get("kind", "")
            if kind == "rebalance":
                layer.stop_rebalance()
            elif kind == "decommission":
                layer.cancel_decommission()
            return {"ok": True}

        if grid_srv is not None:
            grid_srv.register("elastic.status", _elastic_status)
            grid_srv.register("elastic.stop", _elastic_stop)
            # Fleet-sharded migration batches: the coordinator ships
            # listing-page shards here; this node migrates them with
            # its OWN pools layer and returns counters only
            # (object/decom.exec_page — no peer ever checkpoints).
            from minio_tpu.object.decom import exec_page as _exec_page
            grid_srv.register(
                "mig.page",
                lambda p: _exec_page(layer, int(p["src"]), p["b"],
                                     list(p.get("keys") or ()),
                                     p.get("ex") or ()))
        # Every worker may win the coordinator lease; the dispatcher
        # targets each peer NODE's grid plane (its worker 0).
        layer.migration_peers = [client_for(h, p + GRID_PORT_OFFSET)
                                 for h, p in remote_nodes]
        if len(pools) > 1 and is_w0:
            # Orphan-recovery loop: resumes a dead coordinator's walk
            # from its checkpoint once the lease expires.
            layer.start_elastic_janitor()
    if args.audit_webhook:
        from minio_tpu.s3.trace import AuditLogger
        srv.audit = AuditLogger(args.audit_webhook)
    # Async bucket replication: rules + remote targets live in bucket
    # metadata; the scanner hook re-queues PENDING/FAILED versions.
    from minio_tpu.replication import ReplicationEngine
    srv.replicator = ReplicationEngine(layer)
    scanner.on_object.append(srv.replicator.scanner_hook)
    notify_targets = []
    if args.notify_webhook:
        from minio_tpu.events import WebhookTarget
        notify_targets.append(WebhookTarget("webhook",
                                            args.notify_webhook))
    for flag, cls, tid in ((args.notify_mqtt, "MQTTTarget", "mqtt"),
                           (args.notify_nats, "NATSTarget", "nats"),
                           (args.notify_redis, "RedisTarget", "redis")):
        if not flag:
            continue
        import minio_tpu.events as _ev
        broker, _, chan = flag.partition("/")
        if not chan:
            print(f"FATAL: --notify-{tid} needs host:port/"
                  f"{'topic' if tid == 'mqtt' else 'subject' if tid == 'nats' else 'listkey'}",
                  file=sys.stderr)
            return 1
        try:
            notify_targets.append(getattr(_ev, cls)(tid, broker, chan))
        except ValueError:
            print(f"FATAL: --notify-{tid}: {broker!r} is not host:port",
                  file=sys.stderr)
            return 1
    if notify_targets:
        # Store-and-forward notifications; the queue lives on the
        # first local drive so it survives restarts.
        from minio_tpu.events import EventNotifier
        first_local = next((d for p in pools for s in p.sets
                            for d in s.disks
                            if getattr(d, "root", None)), None)
        # Durable queue location: a local drive when we have one, else a
        # per-deployment dir under $HOME (reboot-durable, unlike /tmp).
        store = os.path.join(first_local.root, ".mtpu.sys", "events") \
            if first_local is not None else \
            os.path.join(os.path.expanduser("~"), ".mtpu",
                         f"events-{deployment_id}")
        srv.notifier = EventNotifier(layer, store, targets=notify_targets)
    ftp = None
    if args.ftp_address:
        from minio_tpu.gateway import FTPGateway
        ftp = FTPGateway(layer, creds, address=args.ftp_address)
        ftp.start()
        print(f"minio-tpu serving FTP on {ftp.address}", flush=True)
    # Pre-forked worker wiring (no-op outside worker mode): control
    # pipes, divided admission budgets, cross-process locks and cache
    # generations, SIGTERM drain.
    workers_mod.maybe_attach_worker(srv)
    print(f"minio-tpu serving S3 on {srv.address} "
          f"({len(pools)} pools, {n_sets} sets, {n_drives} drives, "
          f"{'distributed, ' if distributed else ''}"
          f"{'worker ' + worker_id + ', ' if worker_id else ''}"
          f"ec-backend={'tpu' if backend else 'host'})", flush=True)
    srv.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        scanner.stop()
        drive_heal.stop()
        layer.stop_elastic_janitor()
        if getattr(srv, "coherence", None) is not None:
            srv.coherence.stop()
        if ftp is not None:
            # Gateways stop BEFORE the S3 server closes the object
            # layer (their in-flight transfers use it).
            ftp.stop()
        srv.stop()
        if grid_srv is not None:
            grid_srv.stop()
        # Graceful exit: stamp every local drive so the next boot skips
        # the deep crash-recovery sweep (storage/local.recovery_sweep).
        from minio_tpu.storage.local import mark_clean_shutdown
        for d in local_disks.values():
            mark_clean_shutdown(d)
    return 0


if __name__ == "__main__":
    sys.exit(main())
