"""Multi-chip stripe parallelism: the TPU analogue of erasure striping.

The reference parallelises one erasure stripe across n drives
(multiWriter fan-out, reference: cmd/erasure-encode.go:27-110) and
scales out by hashing objects across independent erasure sets
(cmd/erasure-sets.go:663). On a TPU pod the same two axes become a
`jax.sharding.Mesh`:

  * ``stripe`` — data parallelism over independent stripe batches
    (the analogue of set-level scale-out: stripes never talk to each
    other, so this axis needs no collectives for encode);
  * ``shard``  — the k+m shard axis (the analogue of the drive fan-out:
    decode/heal gathers k surviving shards, which becomes an
    ``all_gather`` riding ICI instead of n NVMe/network reads).

The compute body runs under ``shard_map`` so each chip executes the
fused Pallas kernel (rs_device mode="auto": Pallas on TPU, XLA einsum
elsewhere) on its local block; the collectives between blocks are
explicit (`all_gather` on the shard axis, `psum` for the parity check),
mirroring the reference's k-parallel drive reads and write-quorum
accounting.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from minio_tpu.ops import gf256
from minio_tpu.ops import rs_device


def make_mesh(devices=None, stripe_parallel: int | None = None) -> Mesh:
    """A ("stripe", "shard") mesh over the given devices.

    The shard axis gets the largest power-of-two factor <= 4 of the device
    count (shard fan-out is latency-bound, keep it on adjacent chips);
    the rest goes to the embarrassingly-parallel stripe axis.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if stripe_parallel is None:
        shard_par = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
        stripe_parallel = n // shard_par
    shard_par = n // stripe_parallel
    return Mesh(devices.reshape(stripe_parallel, shard_par),
                axis_names=("stripe", "shard"))


def encode_step(mesh: Mesh, k: int, m: int, mode: str = "auto"):
    """Build the jitted full encode step for one (k, m) config.

    Input  : data uint8 [B, k, L], sharded (stripe, -, shard) — the lane
             (byte-offset) axis is split over the shard devices, since the
             GF transform is independent per byte column. B must divide by
             the stripe axis and L by the shard axis (callers pad stripe
             batches to whole tiles anyway).
    Output : shards uint8 [B, k+m, L] sharded over (stripe, shard) — the
             device-side layout from which per-drive writers DMA their
             shard column out — plus a parity self-check scalar psum'd
             over the whole mesh (the device-side analogue of the write
             path verifying parity consistency before commit).
    """
    encode = rs_device.make_encoder(gf256.parity_matrix(k, m), mode=mode)
    # Independent verification path: decode the first min(m, k) data rows
    # back from (the remaining data rows + parity). A DIFFERENT GF matrix
    # (a Vandermonde-submatrix inverse) computes it, so XLA cannot CSE it
    # against the encode — a wrong bit-matrix or flaky chip shows up as a
    # nonzero check, unlike a re-encode of identical inputs.
    n = k + m
    nchk = min(m, k)
    survivors = tuple(range(nchk, n))[:k]
    dec_rows = gf256.decode_matrix(k, m, survivors)[:nchk, :]
    verify = rs_device.make_encoder(dec_rows, mode=mode)

    data_sharding = NamedSharding(mesh, P("stripe", None, "shard"))
    out_sharding = NamedSharding(mesh, P("stripe", "shard", None))

    def local_step(data: jax.Array) -> tuple[jax.Array, jax.Array]:
        # Local block [B/sp, k, L/shp]: every chip runs the fused kernel
        # on its lane slice; no cross-chip traffic inside the hot loop.
        parity = encode(data)
        shards = jnp.concatenate([data, parity], axis=1)  # [b, k+m, l]
        redecoded = verify(shards[:, nchk:, :][:, :k, :])
        check = jnp.sum((redecoded ^ shards[:, :nchk, :]).astype(jnp.int32))
        check = jax.lax.psum(check, ("stripe", "shard"))
        return shards, check

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, which the static VMA checker requires under shard_map.
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("stripe", None, "shard"),),
        out_specs=(P("stripe", None, "shard"), P()), check_vma=False)

    stripe_par, shard_par = mesh.devices.shape

    @jax.jit
    def step(data: jax.Array) -> tuple[jax.Array, jax.Array]:
        assert data.shape[0] % stripe_par == 0, \
            f"batch {data.shape[0]} not divisible by stripe axis {stripe_par}"
        assert data.shape[2] % shard_par == 0, \
            f"lanes {data.shape[2]} not divisible by shard axis {shard_par}"
        shards, check = sharded(data)
        # Redistribute lanes→shard-rows so each shard-axis device holds
        # whole shard rows for its drives (an all-to-all over ICI).
        shards = jax.lax.with_sharding_constraint(shards, out_sharding)
        return shards, check

    return step, data_sharding


def decode_gather_step(mesh: Mesh, k: int, m: int, missing: tuple[int, ...],
                       mode: str = "auto"):
    """Jitted reconstruct of missing DATA shards from k survivors.

    `missing` lists lost shard indices (data or parity); only the data
    rows (< k) are produced, like the reference's DecodeDataBlocks —
    parity re-derives from data on the heal path. Input: survivors uint8
    [B, k, L] (the first k available shard rows, like the reference's
    ReconstructData), sharded over (stripe, shard): each shard-axis
    device holds k/shard_par survivor rows, and the explicit
    ``all_gather`` over the shard axis is the ICI replacement for the
    reference's k parallel drive reads (cmd/erasure-decode.go:127-221).
    """
    n = k + m
    available = tuple(i for i in range(n) if i not in missing)[:k]
    dec = gf256.decode_matrix(k, m, available)
    missing_data = [i for i in missing if i < k]
    reconstruct = rs_device.make_encoder(dec[missing_data, :], mode=mode)

    in_sharding = NamedSharding(mesh, P("stripe", "shard", None))

    shard_par = mesh.devices.shape[1]

    def local_step(survivors: jax.Array) -> jax.Array:
        # survivors local block [B/sp, k/shp, L]: gather the full k rows
        # onto every shard-axis device (ICI all_gather), then reconstruct
        # only this device's lane slice — each chip does 1/shard_par of
        # the GF transform instead of replicating the whole matmul.
        rows = jax.lax.all_gather(survivors, "shard", axis=1, tiled=True)
        lanes = rows.shape[2] // shard_par
        idx = jax.lax.axis_index("shard")
        mine = jax.lax.dynamic_slice_in_dim(rows, idx * lanes, lanes, axis=2)
        return reconstruct(mine)

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, which the static VMA checker requires under shard_map.
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("stripe", "shard", None),),
        out_specs=P("stripe", None, "shard"), check_vma=False)

    stripe_par = mesh.devices.shape[0]

    @jax.jit
    def step(survivors: jax.Array) -> jax.Array:
        assert survivors.shape[0] % stripe_par == 0, \
            f"batch {survivors.shape[0]} not divisible by stripe axis"
        assert survivors.shape[1] % shard_par == 0, \
            f"k={survivors.shape[1]} not divisible by shard axis {shard_par}"
        assert survivors.shape[2] % shard_par == 0, \
            f"lanes {survivors.shape[2]} not divisible by shard axis"
        return sharded(survivors)

    return step, in_sharding
