"""Multi-chip stripe parallelism: the TPU analogue of erasure striping.

The reference parallelises one erasure stripe across n drives
(multiWriter fan-out, reference: cmd/erasure-encode.go:27-110) and
scales out by hashing objects across independent erasure sets
(cmd/erasure-sets.go:663). On a TPU pod the same two axes become a
`jax.sharding.Mesh`:

  * ``stripe`` — data parallelism over independent stripe batches
    (the analogue of set-level scale-out: stripes never talk to each
    other, so this axis needs no collectives for encode);
  * ``shard``  — the k+m shard axis (the analogue of the drive fan-out:
    decode/heal gathers k surviving shards, which becomes an
    ``all_gather`` riding ICI instead of n NVMe/network reads).

Everything here is pure-jit SPMD: the same program runs on every chip,
XLA inserts the collectives implied by the sharding annotations.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from minio_tpu.ops import gf256
from minio_tpu.ops import rs_device


def make_mesh(devices=None, stripe_parallel: int | None = None) -> Mesh:
    """A ("stripe", "shard") mesh over the given devices.

    The shard axis gets the largest power-of-two factor <= 4 of the device
    count (shard fan-out is latency-bound, keep it on adjacent chips);
    the rest goes to the embarrassingly-parallel stripe axis.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if stripe_parallel is None:
        shard_par = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
        stripe_parallel = n // shard_par
    shard_par = n // stripe_parallel
    return Mesh(devices.reshape(stripe_parallel, shard_par),
                axis_names=("stripe", "shard"))


def encode_step(mesh: Mesh, k: int, m: int):
    """Build the jitted full encode step for one (k, m) config.

    Input  : data uint8 [B, k, L], sharded over stripes.
    Output : shards uint8 [B, k+m, L] sharded over (stripe, shard) — the
             device-side layout from which per-drive writers DMA their
             shard column out — plus a parity self-check scalar psum'd
             over the whole mesh (the device-side analogue of the write
             path verifying parity consistency before commit).
    """
    encode = rs_device.make_encoder(gf256.parity_matrix(k, m), mode="xla")
    # Independent verification path: decode the first min(m, k) data rows
    # back from (the remaining data rows + parity). A DIFFERENT GF matrix
    # (a Vandermonde-submatrix inverse) computes it, so XLA cannot CSE it
    # against the encode — a wrong bit-matrix or flaky chip shows up as a
    # nonzero check, unlike a re-encode of identical inputs.
    n = k + m
    nchk = min(m, k)
    survivors = tuple(range(nchk, n))[:k]
    dec_rows = gf256.decode_matrix(k, m, survivors)[:nchk, :]
    verify = rs_device.make_encoder(dec_rows, mode="xla")

    data_sharding = NamedSharding(mesh, P("stripe", None, None))
    out_sharding = NamedSharding(mesh, P("stripe", "shard", None))

    @jax.jit
    def step(data: jax.Array) -> tuple[jax.Array, jax.Array]:
        parity = encode(data)
        shards = jnp.concatenate([data, parity], axis=1)  # [B, k+m, L]
        shards = jax.lax.with_sharding_constraint(shards, out_sharding)
        redecoded = verify(shards[:, nchk:, :][:, :k, :])
        check = jnp.sum((redecoded ^ shards[:, :nchk, :]).astype(jnp.int32))
        return shards, check

    return step, data_sharding


def decode_gather_step(mesh: Mesh, k: int, m: int, missing: tuple[int, ...]):
    """Jitted reconstruct of missing DATA shards from k survivors.

    `missing` lists lost shard indices (data or parity); only the data
    rows (< k) are produced, like the reference's DecodeDataBlocks —
    parity re-derives from data on the heal path. Input: survivors uint8
    [B, k, L] (the first k available shard rows, like the reference's
    ReconstructData), sharded over (stripe, shard) — the gather of
    survivor rows onto each chip is XLA's all_gather over the shard
    axis, the ICI replacement for the reference's k parallel drive reads
    (cmd/erasure-decode.go:127-221).
    """
    n = k + m
    available = tuple(i for i in range(n) if i not in missing)[:k]
    dec = gf256.decode_matrix(k, m, available)
    missing_data = [i for i in missing if i < k]
    reconstruct = rs_device.make_encoder(dec[missing_data, :], mode="xla")

    in_sharding = NamedSharding(mesh, P("stripe", "shard", None))
    step = jax.jit(reconstruct)
    return step, in_sharding
