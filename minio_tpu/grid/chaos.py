"""Grid chaos injection for the in-container cluster harness.

A node process started with `MTPU_GRID_CHAOS=<path>` consults that JSON
file before every grid frame it sends or accepts, so the cluster
harness (tests/cluster.py) can partition, jitter, or hang a LIVE node
from outside the process — the node-level twin of the drive-level
NaughtyDisk/HungDisk wrappers, usable against real spawned servers
where in-process wrappers cannot reach.

File contents (absent/empty file or unset env = no chaos):

    {"mode": "blackhole"}            every grid connect/send/accept
                                     fails — a hard partition; peers
                                     see connection errors immediately
    {"mode": "drop"}                 inbound request frames vanish
                                     silently — callers time out (the
                                     asymmetric "black hole" shape)
    {"mode": "delay", "seconds": s}  every frame pays `s` seconds —
                                     WAN jitter / a saturated NIC
    {"drive_delay": s}               storage RPC handlers sleep `s`
                                     before running — a hung REMOTE
                                     drive (local drives use HungDisk)

Modes compose with drive_delay in one file. The file is re-stat()ed at
most every 50 ms so the hot path pays one monotonic compare between
polls; processes without the env var pay a single module-global check.
"""

from __future__ import annotations

import json
import os
import time

ENV = "MTPU_GRID_CHAOS"

_PATH = os.environ.get(ENV) or None
_POLL_S = 0.05
_mtime: float = -1.0
_polled_at: float = 0.0
_cfg: dict = {}


class ChaosInjected(Exception):
    """Raised on blackholed operations (mapped to GridError upstream)."""


def _load() -> dict:
    global _mtime, _polled_at, _cfg
    now = time.monotonic()
    if now - _polled_at < _POLL_S:
        return _cfg
    _polled_at = now
    try:
        mtime = os.stat(_PATH).st_mtime_ns
    except OSError:
        _mtime, _cfg = -1.0, {}
        return _cfg
    if mtime == _mtime:
        return _cfg
    _mtime = mtime
    try:
        with open(_PATH, encoding="utf-8") as fh:
            loaded = json.load(fh)
        _cfg = loaded if isinstance(loaded, dict) else {}
    except (OSError, ValueError):
        _cfg = {}
    return _cfg


def active() -> bool:
    return _PATH is not None


def net(point: str) -> None:
    """Gate one network step. `point` is "connect", "send" or "recv";
    blackhole raises at every point, delay sleeps at send/recv."""
    if _PATH is None:
        return
    cfg = _load()
    mode = cfg.get("mode")
    if mode == "blackhole":
        raise ChaosInjected(f"grid chaos blackhole ({point})")
    if mode == "delay" and point != "connect":
        try:
            time.sleep(float(cfg.get("seconds", 0.05)))
        except (TypeError, ValueError):
            pass


def drop_inbound() -> bool:
    """True when an inbound request frame should vanish silently
    (callers time out instead of seeing a connection error)."""
    if _PATH is None:
        return False
    return _load().get("mode") == "drop"


def drive_delay() -> float:
    """Seconds every storage RPC handler should hang before running."""
    if _PATH is None:
        return 0.0
    try:
        return float(_load().get("drive_delay", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _reset_for_tests() -> None:
    """Re-read the env var (tests monkeypatch it after import)."""
    global _PATH, _mtime, _polled_at, _cfg
    _PATH = os.environ.get(ENV) or None
    _mtime, _polled_at, _cfg = -1.0, 0.0, {}
