"""grid: the node-to-node RPC mesh (distributed runtime backbone).

The analogue of the reference's internal/grid (one muxed websocket per
server pair carrying typed RPC + streams, internal/grid/README.md):
here one TCP connection per node pair carries length-prefixed msgpack
frames, multiplexing unary calls and streaming responses, with
auto-reconnect. Small hot calls (metadata, locks) and bulk shard bytes
share the connection; frames are bounded so bulk transfers cannot
starve lock traffic.
"""

from minio_tpu.grid.wire import GridError, RemoteCallError  # noqa: F401
from minio_tpu.grid.client import GridClient, client_for  # noqa: F401
from minio_tpu.grid.server import GridServer  # noqa: F401
