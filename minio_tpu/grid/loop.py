"""Grid epoll plane: one poller thread parks every grid connection.

PR-12's mesh spent one blocking reader thread per peer connection on
each side of every link — an N-node fleet burns O(N) threads per
process just waiting on recv, and every received bulk chunk is a fresh
msgpack-decoded bytes object. This module is the grid twin of the
PR-16 client event loop (s3/eventloop.py): all grid sockets — client
connections out and server connections in — register on a single
process-wide epoll set serviced by one thread. The poller owns ALL
reads; frame reassembly happens here (v1 msgpack control frames and v2
raw bulk frames, see grid/wire.py), raw payloads land directly in
pooled bufpool leases via recv_into, and decoded frames are handed to
per-connection callbacks (the client's demux, the server's dispatch).
Writes stay blocking sendall under per-connection write locks held one
frame (or one raw slice) at a time, exactly as before, so lock and
coherence RPCs interleave between a bulk transfer's slices.

Also here: the shared raw-frame SEND helpers. `send_raw_fd` ships a
file region straight from its fd to the socket with os.sendfile — the
payload bytes never surface into Python — and `send_raw_buf` ships an
in-memory buffer as raw frames without a msgpack wrap. Both take one
credit per slice when the stream is flow-controlled (`Credit`,
replenished by T_WIN frames), so a receiver that stops consuming
stalls the sender instead of ballooning frames into its reassembly
queues.

The kill switch `MTPU_GRID_NATIVE=off` (grid/wire.py) keeps sockets on
the v1 blocking-reader-thread path; this module then stays entirely
idle.

Environment:
  MTPU_GRID_STREAM_WINDOW   per-stream credit window, frames
                            (default 32; one frame <= 1 MiB)
  MTPU_GRID_STREAM_STALL_S  seconds a flow-controlled sender waits for
                            credit before failing the stream (default 60)
"""

from __future__ import annotations

import os
import select
import socket
import threading
import time
from typing import Callable, Optional

import msgpack

from minio_tpu.grid import wire
from minio_tpu.utils.env import env_num as _env_num

_RECV = 256 << 10


def stream_window() -> int:
    return max(1, _env_num("MTPU_GRID_STREAM_WINDOW", 32, int))


def stream_stall_s() -> float:
    return max(0.05, _env_num("MTPU_GRID_STREAM_STALL_S", 60.0))


def available() -> bool:
    """The poller needs epoll (Linux); elsewhere the v1 reader-thread
    path keeps working unchanged."""
    return hasattr(select, "epoll")


class Credit:
    """Counting credit window for one stream. The sender takes one
    credit per frame; the receiver grants credits back (T_WIN) as its
    consumer drains frames. close() releases waiters with failure —
    connection loss must not leave senders parked until the stall
    timeout."""

    __slots__ = ("_cv", "_n", "closed", "waits")

    def __init__(self, n: int):
        self._cv = threading.Condition()
        self._n = int(n)
        self.closed = False
        self.waits = 0

    def grant(self, k: int) -> None:
        with self._cv:
            self._n += int(k)
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self.closed = True
            self._cv.notify_all()

    def take(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._n <= 0 and not self.closed:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.waits += 1
                self._cv.wait(left)
            if self.closed or self._n <= 0:
                return False
            self._n -= 1
            return True


class _Conn:
    """Per-connection frame reassembly (v1 msgpack + v2 raw). A raw
    payload whose header has been parsed streams the rest of its bytes
    straight into a pooled lease via recv_into — no intermediate
    bytes object for the bulk of a transfer."""

    __slots__ = ("sock", "fd", "on_msg", "on_close", "buf",
                 "raw_lease", "raw_view", "raw_mux", "raw_need",
                 "raw_have")

    def __init__(self, sock, on_msg: Callable[[dict], None],
                 on_close: Optional[Callable[[], None]]):
        self.sock = sock
        self.fd = sock.fileno()
        self.on_msg = on_msg
        self.on_close = on_close
        self.buf = bytearray()
        self.raw_lease = None
        self.raw_view: Optional[memoryview] = None
        self.raw_mux = 0
        self.raw_need = 0
        self.raw_have = 0

    def on_readable(self, poller: "GridPoller") -> None:
        if self.raw_lease is not None and not self.buf \
                and self.raw_have < self.raw_need:
            n = self.sock.recv_into(
                self.raw_view[self.raw_have:self.raw_need])
            if not n:
                raise wire.GridError("connection closed")
            self.raw_have += n
            poller.raw_rx_bytes_total += n
            if self.raw_have == self.raw_need:
                self._deliver_raw(poller)
            return
        data = self.sock.recv(_RECV)
        if not data:
            raise wire.GridError("connection closed")
        self.buf += data
        self._parse(poller)

    def _parse(self, poller: "GridPoller") -> None:
        buf = self.buf
        while True:
            if self.raw_lease is not None:
                take = min(len(buf), self.raw_need - self.raw_have)
                if take:
                    self.raw_view[self.raw_have:self.raw_have + take] = \
                        buf[:take]
                    del buf[:take]
                    self.raw_have += take
                    poller.raw_rx_bytes_total += take
                if self.raw_have < self.raw_need:
                    return
                self._deliver_raw(poller)
                continue
            if len(buf) < 4:
                return
            (word,) = wire._LEN.unpack_from(buf, 0)
            if word & wire._RAW_BIT:
                if len(buf) < 8:
                    return
                need = (word & ~wire._RAW_BIT) - 4
                if need < 0 or need > wire.MAX_FRAME:
                    raise wire.GridError(f"oversized raw frame: {word}")
                (self.raw_mux,) = wire._LEN.unpack_from(buf, 4)
                del buf[:8]
                from minio_tpu.io.bufpool import global_pool
                self.raw_need = need
                self.raw_have = 0
                self.raw_lease = global_pool().lease(max(need, 1))
                self.raw_view = self.raw_lease.view(need) if need else None
                if need == 0:
                    self._deliver_raw(poller)
                continue
            if word > wire.MAX_FRAME:
                raise wire.GridError(f"oversized frame: {word}")
            if len(buf) < 4 + word:
                return
            msg = msgpack.unpackb(bytes(buf[4:4 + word]), raw=False,
                                  strict_map_key=False)
            del buf[:4 + word]
            poller.frames_total += 1
            self.on_msg(msg)

    def _deliver_raw(self, poller: "GridPoller") -> None:
        lease, view = self.raw_lease, self.raw_view
        self.raw_lease = self.raw_view = None
        poller.raw_rx_frames_total += 1
        # The callback owns the lease from here: it must release() it
        # (or hand it to a consumer that will).
        self.on_msg({"t": wire.T_CHUNK, "m": self.raw_mux,
                     "p": view if view is not None else b"",
                     "lease": lease, "raw": True})


class GridPoller:
    """One epoll set + one thread for every registered grid socket."""

    def __init__(self):
        self._ep = select.epoll()
        self._conns: dict[int, _Conn] = {}
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self.frames_total = 0
        self.raw_rx_frames_total = 0
        self.raw_rx_bytes_total = 0
        self.conns_dropped_total = 0

    def register(self, sock, on_msg: Callable[[dict], None],
                 on_close: Optional[Callable[[], None]] = None) -> None:
        conn = _Conn(sock, on_msg, on_close)
        with self._mu:
            self._conns[conn.fd] = conn
            self._ep.register(conn.fd, select.EPOLLIN)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="grid-poller", daemon=True)
                self._thread.start()

    def discard(self, sock) -> None:
        """Forget a socket without closing it or firing on_close (the
        caller is tearing the connection down itself)."""
        try:
            fd = sock.fileno()
        except OSError:
            fd = -1
        with self._mu:
            conn = self._conns.pop(fd, None) if fd >= 0 else None
            if conn is None:
                for k, c in list(self._conns.items()):
                    if c.sock is sock:
                        conn = self._conns.pop(k)
                        fd = k
                        break
            if conn is None:
                return
            try:
                self._ep.unregister(fd)
            except (OSError, ValueError):
                pass
        lease, conn.raw_lease = conn.raw_lease, None
        if lease is not None:
            lease.release()

    def conns(self) -> int:
        with self._mu:
            return len(self._conns)

    def _run(self) -> None:
        while not self._stopping:
            try:
                events = self._ep.poll(1.0)
            except (OSError, ValueError):
                if self._stopping:
                    return
                time.sleep(0.05)
                continue
            for fd, _ev in events:
                with self._mu:
                    conn = self._conns.get(fd)
                if conn is None:
                    continue
                try:
                    conn.on_readable(self)
                except Exception:  # noqa: BLE001 - one conn, not the loop
                    self._drop(conn)

    def _drop(self, conn: _Conn) -> None:
        self.conns_dropped_total += 1
        self.discard(conn.sock)
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.on_close is not None:
            try:
                conn.on_close()
            except Exception:  # noqa: BLE001 - observers must not kill loop
                pass


_POLLER: Optional[GridPoller] = None
_POLLER_MU = threading.Lock()


def poller() -> GridPoller:
    global _POLLER
    if _POLLER is None:
        with _POLLER_MU:
            if _POLLER is None:
                _POLLER = GridPoller()
    return _POLLER


def discard(sock) -> None:
    """Forget `sock` if a poller exists; never instantiates one."""
    p = _POLLER
    if p is not None:
        p.discard(sock)


# -- raw-frame send helpers (shared by grid server and client) ----------

# Send-side transfer counters (module-level; += under the GIL is
# metrics-grade, matching the per-client counters elsewhere).
sendfile_transfers_total = 0
sendfile_bytes_total = 0
raw_tx_frames_total = 0
raw_tx_bytes_total = 0
credit_stalls_total = 0


def _take_credit(credit: Optional[Credit], stall: float) -> None:
    global credit_stalls_total
    if credit is not None and not credit.take(stall):
        credit_stalls_total += 1
        raise wire.GridError("stream credit stall (receiver not draining)")


def send_raw_fd(sock, wlock, mux: int, fd: int, offset: int, length: int,
                credit: Optional[Credit] = None,
                stall: Optional[float] = None) -> int:
    """Ship [offset, offset+length) of `fd` to `sock` as raw frames via
    os.sendfile — the payload never surfaces into Python. One wlock
    hold and one credit per slice, so small control frames (locks,
    coherence pushes) interleave between slices of a bulk transfer.
    A zero-length source still emits one empty raw frame (stream-shape
    parity with the msgpack path's single empty chunk)."""
    global sendfile_transfers_total, sendfile_bytes_total
    global raw_tx_frames_total, raw_tx_bytes_total
    from minio_tpu.grid import chaos
    stall = stream_stall_s() if stall is None else stall
    frames = 0
    while length > 0 or frames == 0:
        n = min(length, wire.RAW_SLICE)
        _take_credit(credit, stall)
        with wlock:
            chaos.net("send")
            sock.sendall(wire.pack_raw_header(mux, n))
            off = offset
            end = offset + n
            while off < end:
                sent = os.sendfile(sock.fileno(), fd, off, end - off)
                if sent == 0:
                    raise wire.GridError("sendfile: source truncated")
                off += sent
        offset += n
        length -= n
        frames += 1
        sendfile_bytes_total += n
        raw_tx_frames_total += 1
        raw_tx_bytes_total += n
    sendfile_transfers_total += 1
    return frames


def send_raw_buf(sock, wlock, mux: int, data,
                 credit: Optional[Credit] = None,
                 stall: Optional[float] = None) -> int:
    """Ship an in-memory buffer as raw frames (no msgpack wrap, no
    per-chunk bytes copies — sendall works straight off memoryview
    slices). Same slice/credit/wlock granularity as send_raw_fd."""
    global raw_tx_frames_total, raw_tx_bytes_total
    from minio_tpu.grid import chaos
    stall = stream_stall_s() if stall is None else stall
    view = memoryview(data)
    if view.ndim != 1 or view.itemsize != 1:
        view = view.cast("B")
    total = len(view)
    off = 0
    frames = 0
    while off < total or frames == 0:
        n = min(total - off, wire.RAW_SLICE)
        _take_credit(credit, stall)
        with wlock:
            chaos.net("send")
            sock.sendall(wire.pack_raw_header(mux, n))
            if n:
                sock.sendall(view[off:off + n])
        off += n
        frames += 1
        raw_tx_frames_total += 1
        raw_tx_bytes_total += n
    return frames


def stats() -> dict:
    """Counter snapshot for the Prometheus render and admin info."""
    p = _POLLER
    return {
        "native": wire.native_enabled(),
        "conns": p.conns() if p is not None else 0,
        "frames": p.frames_total if p is not None else 0,
        "raw_rx_frames": p.raw_rx_frames_total if p is not None else 0,
        "raw_rx_bytes": p.raw_rx_bytes_total if p is not None else 0,
        "conns_dropped": p.conns_dropped_total if p is not None else 0,
        "raw_tx_frames": raw_tx_frames_total,
        "raw_tx_bytes": raw_tx_bytes_total,
        "sendfile_transfers": sendfile_transfers_total,
        "sendfile_bytes": sendfile_bytes_total,
        "credit_stalls": credit_stalls_total,
    }
