"""Peer control plane: change-notification fan-out.

The analogue of the reference's NotificationSys + peer REST client
(cmd/notification.go:49, cmd/peer-rest-client.go:304): a node that
mutates shared cluster state (bucket metadata, IAM, config) broadcasts
a reload to every peer so their caches drop immediately instead of
serving stale authorization or versioning state for up to a cache TTL.
The TTL remains the fallback for peers that are down or unreachable at
broadcast time — they re-read the (already-persisted) truth from the
drives within one TTL of coming back.
"""

from __future__ import annotations

import threading
from typing import Callable

from minio_tpu.utils import tracing

RELOAD_HANDLER = "peer.reload"

# Fan-out outcome counters (module-level: one Prometheus scrape line
# aggregates every notifier instance in the process). Best-effort
# failures stay best-effort — but never invisible.
_stats_mu = threading.Lock()
NOTIFY_SENT = 0
NOTIFY_FAILED = 0


def notify_stats() -> dict:
    with _stats_mu:
        return {"sent": NOTIFY_SENT, "failed": NOTIFY_FAILED}

# Reload kinds a peer understands.
KIND_IAM = "iam"
KIND_BUCKET_META = "bucket-meta"
KIND_CONFIG = "config"
KIND_DECOM = "decom"
# A bucket's namespace changed on the sending node: drop listing walk
# streams (object/metacache.py) so the peer's next listing re-walks
# immediately instead of serving pre-write names.
KIND_LISTING = "listing"


class PeerNotifier:
    """Best-effort synchronous fan-out to every peer.

    Broadcasts run all peers in parallel and wait up to `timeout` for
    each, so a credential revocation or policy change has reached every
    reachable node by the time the admin call returns (the reference's
    NotificationSys collects per-peer errors the same way). Failures
    are swallowed: the state is already quorum-persisted, and the
    peer's cache TTL bounds its staleness.
    """

    def __init__(self, clients, timeout: float = 2.0):
        self._clients = list(clients)
        self._timeout = timeout

    def broadcast(self, kind: str, bucket: str = "") -> None:
        if not self._clients:
            return
        payload = {"kind": kind, "bucket": bucket}
        threads = [threading.Thread(target=self._one, args=(c, payload),
                                    daemon=True)
                   for c in self._clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self._timeout)

    def _one(self, client, payload) -> None:
        global NOTIFY_SENT, NOTIFY_FAILED
        try:
            client.call(RELOAD_HANDLER, payload, timeout=self._timeout)
            with _stats_mu:
                NOTIFY_SENT += 1
        except Exception as e:  # noqa: BLE001 - peer down; TTL fallback —
            # but the swallowed failure is counted and named: a silent
            # best-effort path that fails every time is an outage.
            with _stats_mu:
                NOTIFY_FAILED += 1
            tracing.slow_event(
                "grid", "peer.notify-failed",
                tags={"peer": f"{getattr(client, 'host', '?')}:"
                              f"{getattr(client, 'port', '?')}",
                      "kind": payload.get("kind", ""),
                      "error": f"{type(e).__name__}: {e}"})


def make_reload_handler(iam=None, object_layer=None,
                        apply_config: Callable | None = None):
    """Build the receiving side: a grid handler that drops the local
    cache named by the payload (reference: cmd/peer-rest-server.go's
    LoadBucketMetadataHandler / LoadUserHandler / SignalServiceHandler
    family, collapsed into one keyed endpoint)."""

    def handler(payload):
        kind = (payload or {}).get("kind", "")
        if kind == KIND_IAM and iam is not None:
            iam.invalidate()
        elif kind == KIND_BUCKET_META and object_layer is not None:
            object_layer.invalidate_bucket_meta(
                (payload or {}).get("bucket", ""))
        elif kind == KIND_CONFIG and apply_config is not None:
            try:
                apply_config()
            except Exception:  # noqa: BLE001 - bad config must not kill RPC
                pass
        elif kind == KIND_LISTING and object_layer is not None:
            bucket = (payload or {}).get("bucket", "")
            # Bump WITHOUT re-broadcast: the originating node already
            # fanned out; echoing would ping-pong bumps forever.
            from minio_tpu.s3.metrics import layer_sets
            for es in layer_sets(object_layer):
                mc = getattr(es, "metacache", None)
                if mc is not None:
                    mc.bump(bucket, broadcast=False)
        elif kind == KIND_DECOM and object_layer is not None:
            # A drain started/finished on another node: re-sync this
            # node's pool placement exclusions from persisted state.
            sync = getattr(object_layer, "sync_decommission_markers",
                           None)
            if sync is not None:
                try:
                    sync()
                except Exception:  # noqa: BLE001 - next boot re-syncs
                    pass
        return "ok"

    return handler
