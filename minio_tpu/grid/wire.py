"""Wire framing for the grid mesh.

Frame = 4-byte big-endian length + one msgpack map:

    {"t": TYPE, "m": mux_id, ...}

      T_REQ    {"h": handler, "p": payload}      unary call
      T_RESP   {"p": payload}                    unary result
      T_ERR    {"e": code, "msg": str}           call failed
      T_SREQ   {"h": handler, "p": payload}      open a response stream
      T_CHUNK  {"p": item}                       one stream item
      T_EOF    {}                                stream end
      T_PING / T_PONG                            keepalive

Payloads are anything msgpack can carry (maps/lists/bytes/str/ints).
The reference's split between grid RPC (small hot calls) and HTTP
streams (bulk bytes) maps onto T_REQ vs T_SREQ/T_CHUNK on the same
multiplexed connection (internal/grid/README.md; the frame cap keeps
bulk chunks from head-of-line-blocking lock traffic).
"""

from __future__ import annotations

import struct
from typing import Optional

import msgpack

T_REQ = 0
T_RESP = 1
T_ERR = 2
T_SREQ = 3
T_CHUNK = 4
T_EOF = 5
T_PING = 6
T_PONG = 7

# A single frame never exceeds this; callers chunk larger payloads.
MAX_FRAME = 32 << 20
_LEN = struct.Struct(">I")


class GridError(Exception):
    """Transport-level failure (connect, frame, timeout)."""


class RemoteCallError(GridError):
    """The remote handler raised; `code` maps back to a local exception."""

    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(f"{code}: {msg}" if msg else code)


def pack_frame(msg: dict) -> bytes:
    blob = msgpack.packb(msg, use_bin_type=True)
    if len(blob) > MAX_FRAME:
        raise GridError(f"frame too large: {len(blob)} bytes")
    return _LEN.pack(len(blob)) + blob


def read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise GridError("connection closed")
        buf += got
    return bytes(buf)


def read_frame(sock) -> dict:
    (length,) = _LEN.unpack(read_exact(sock, 4))
    if length > MAX_FRAME:
        raise GridError(f"oversized frame: {length}")
    return msgpack.unpackb(read_exact(sock, length), raw=False,
                           strict_map_key=False)
