"""Wire framing for the grid mesh (v2: msgpack control + raw bulk).

Control frame = 4-byte big-endian length + one msgpack map:

    {"t": TYPE, "m": mux_id, ...}

      T_REQ    {"h": handler, "p": payload}      unary call
      T_RESP   {"p": payload}                    unary result
      T_ERR    {"e": code, "msg": str}           call failed
      T_SREQ   {"h": handler, "p": payload,      open a response stream
                "w": window}                     (initial credit, chunks)
      T_CHUNK  {"p": item}                       one stream item
      T_EOF    {}                                stream end
      T_PING / T_PONG                            keepalive
      T_WIN    {"n": credits}                    grant stream credits

Trace propagation (armed callers only — a disarmed caller emits these
frames byte-identical to pre-trace builds):

      T_REQ/T_SREQ may carry  "tc": {"i": trace_id, "s": parent_span,
                                     "a": 1, "n": caller_node}
      T_RESP/T_ERR/T_EOF may carry back
                              "ts": {"spans": [...], "dropped": n,
                                     "q": queue_wait_ms,
                                     "v": service_ms, "node": peer}

The peer executes the handler under a trace context seeded from "tc"
and ships its completed span subtree ("ts", ring-capped at
MTPU_TRACE_REMOTE_MAX) piggybacked on the reply; the caller stitches
it under an explicit `wire` span (utils/tracing.stitch_wire).

Raw frame (v2) = the same 4-byte length word with the high bit set,
followed by a 4-byte big-endian mux id, followed by exactly
``length & 0x7fffffff - 4`` payload bytes:

    [len | 0x80000000][mux][payload ...]

Raw frames carry bulk stream bytes (shard files, DARE packages)
without a msgpack encode/decode on either side: the sender can push
them straight from a drive fd with ``os.sendfile`` and the receiver
lands them in a pooled bufpool lease. They are semantically a T_CHUNK
whose item is the payload bytes. Legacy (v1) peers never emit the
high bit — MAX_FRAME is far below 2**31 — so the two framings coexist
on one connection and ``MTPU_GRID_NATIVE=off`` reverts to pure v1.

Payloads are anything msgpack can carry (maps/lists/bytes/str/ints).
The reference's split between grid RPC (small hot calls) and HTTP
streams (bulk bytes) maps onto T_REQ vs T_SREQ/T_CHUNK on the same
multiplexed connection (internal/grid/README.md; the frame cap and
per-stream credit windows keep bulk chunks from
head-of-line-blocking lock traffic).
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Tuple, Union

import msgpack

T_REQ = 0
T_RESP = 1
T_ERR = 2
T_SREQ = 3
T_CHUNK = 4
T_EOF = 5
T_PING = 6
T_PONG = 7
T_WIN = 8

# A single frame never exceeds this; callers chunk larger payloads.
MAX_FRAME = 32 << 20
_LEN = struct.Struct(">I")
_RAW_BIT = 0x80000000
_RAW_HDR = struct.Struct(">II")

# Raw payload slice size for sendfile/recv loops. One slice per write
# lock acquisition, so small control frames interleave between slices.
RAW_SLICE = 1 << 20


def native_enabled() -> bool:
    """MTPU_GRID_NATIVE kill switch (default on). ``off`` reverts the
    mesh to the v1 per-frame msgpack path, byte-identical."""
    return os.environ.get("MTPU_GRID_NATIVE", "on").lower() not in (
        "off", "0", "false", "no")


class RawFile:
    """Stream item shipped as raw frames straight from the file via
    os.sendfile (zero Python-level copies send-side). length < 0 means
    to end-of-file, resolved at send time."""

    __slots__ = ("path", "offset", "length")

    def __init__(self, path: str, offset: int = 0, length: int = -1):
        self.path = path
        self.offset = offset
        self.length = length


class RawBytes:
    """Stream item shipped as raw frames from an in-memory buffer
    (no msgpack wrap; sendall off memoryview slices)."""

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data


class GridError(Exception):
    """Transport-level failure (connect, frame, timeout)."""


class RemoteCallError(GridError):
    """The remote handler raised; `code` maps back to a local exception."""

    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(f"{code}: {msg}" if msg else code)


def pack_frame(msg: dict) -> bytes:
    blob = msgpack.packb(msg, use_bin_type=True)
    if len(blob) > MAX_FRAME:
        raise GridError(f"frame too large: {len(blob)} bytes")
    return _LEN.pack(len(blob)) + blob


def pack_raw_header(mux: int, payload_len: int) -> bytes:
    """Header for a raw bulk frame: [len|RAW_BIT][mux]. The length
    word counts the mux field plus the payload bytes that follow."""
    if payload_len > MAX_FRAME:
        raise GridError(f"raw frame too large: {payload_len} bytes")
    return _RAW_HDR.pack((payload_len + 4) | _RAW_BIT, mux)


def read_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise GridError("connection closed")
        buf += got
    return bytes(buf)


def read_frame(sock) -> dict:
    """v1 reader: one msgpack control frame. Raw frames surface as a
    synthetic ``{"t": T_CHUNK, "m": mux, "p": bytes, "raw": True}``
    so blocking readers stay correct against a v2 sender."""
    (length,) = _LEN.unpack(read_exact(sock, 4))
    if length & _RAW_BIT:
        payload_len = (length & ~_RAW_BIT) - 4
        if payload_len < 0 or payload_len > MAX_FRAME:
            raise GridError(f"oversized raw frame: {length}")
        (mux,) = _LEN.unpack(read_exact(sock, 4))
        return {"t": T_CHUNK, "m": mux, "p": read_exact(sock, payload_len),
                "raw": True}
    if length > MAX_FRAME:
        raise GridError(f"oversized frame: {length}")
    return msgpack.unpackb(read_exact(sock, length), raw=False,
                           strict_map_key=False)
