"""Grid client: one muxed connection per remote node.

The analogue of the reference's grid.Connection / muxClient
(internal/grid/connection.go, muxclient.go): all calls from this
process to one peer share a single TCP connection; a background reader
demultiplexes responses to per-call queues. Connection loss fails all
in-flight calls (the storage layer treats that as a per-drive fault and
its quorum logic absorbs it) and the next call reconnects.

Peer health rides a per-peer circuit breaker mirroring the drive-health
breaker (storage/health.DiskHealthWrapper): `trip_after` consecutive
TRANSPORT failures open it, open calls fail in microseconds instead of
paying a connect timeout each, and a single half-open probe per
cooldown window re-closes it when the peer returns. The cooldown
doubles (jittered, bounded) across consecutive failed probes so a
long-dead peer is probed ever more lazily — the bounded reconnect
backoff — while a peer that was merely restarting recovers within one
base cooldown. Remote handler errors (RemoteCallError) never trip the
breaker: the peer answered; the handler's exception is the caller's
semantics, not peer death.

Environment:
  MTPU_GRID_TRIP_AFTER    consecutive transport faults that open the
                          breaker (default 3)
  MTPU_GRID_COOLDOWN      base breaker cooldown seconds (default 0.5)
  MTPU_GRID_COOLDOWN_MAX  backoff ceiling seconds (default 15)
"""

from __future__ import annotations

import itertools
import os
import queue
import random
import socket
import threading
import time
from typing import Callable, Iterator, Optional

from minio_tpu.grid import chaos, loop, wire
from minio_tpu.grid.wire import GridError, RemoteCallError
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import DeadlineExceeded
from minio_tpu.utils.env import env_num as _env_num

_SENTINEL_ERR = "__conn_lost__"


class GridClient:
    def __init__(self, host: str, port: int, connect_timeout: float = 5.0,
                 call_timeout: float = 60.0, send_retries: int = 2,
                 retry_backoff: float = 0.05,
                 trip_after: Optional[int] = None,
                 cooldown: Optional[float] = None,
                 cooldown_max: Optional[float] = None):
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        self.call_timeout = call_timeout
        # Send-phase retries: connect/reset failures BEFORE a reply
        # could exist are transient (peer restarting, conn replaced)
        # and safe to retry — the request was never processed. Reply
        # timeouts and remote errors are NEVER retried here, and no
        # retry runs against an exhausted request deadline.
        self.send_retries = max(0, send_retries)
        self.retry_backoff = retry_backoff
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()          # guards connect + state maps
        # Socket writes serialize on their own lock, held per FRAME only:
        # registering new calls (and timing out old ones) never waits on
        # another call's in-flight sendall, and bulk transfers chunked
        # into frames let lock RPCs interleave between chunks
        # (reference: the grid/HTTP-stream split with frame-granular
        # scheduling, internal/grid/README.md).
        self._wmu = threading.Lock()
        self._mux = itertools.count(1)
        # mux -> (socket it was sent on, reply queue): a dying socket's
        # reader must only fail calls sent on THAT socket, never calls
        # already re-registered on a newer connection.
        self._pending: dict[int, tuple[socket.socket, "queue.Queue[dict]"]] \
            = {}
        self._reader: Optional[threading.Thread] = None
        # mux -> Credit for client-push (sink) streams; T_WIN grants
        # from the peer land here, never in the reply queue.
        self._credits: dict[int, loop.Credit] = {}
        # Monotonic stamp of the last frame received on the CURRENT
        # connection: a per-call timeout while other frames are still
        # flowing is that stream's problem (slow/hung handler), not
        # peer death — it must not feed the breaker or disturb the
        # other in-flight streams on the shared connection.
        self._last_rx = 0.0
        # -- circuit breaker (mirrors the drive-health breaker) --------
        self.trip_after = trip_after if trip_after is not None \
            else _env_num("MTPU_GRID_TRIP_AFTER", 3, int)
        self.cooldown = cooldown if cooldown is not None \
            else _env_num("MTPU_GRID_COOLDOWN", 0.5)
        self.cooldown_max = cooldown_max if cooldown_max is not None \
            else _env_num("MTPU_GRID_COOLDOWN_MAX", 15.0)
        self._consecutive = 0
        self._open_since = 0.0               # 0 = closed
        self._open_for = 0.0                 # current (jittered) cooldown
        self._probe_streak = 0               # consecutive failed probes
        self._half_open_probe = False
        self._probe_started = 0.0
        self._probe_owner = 0                # thread holding the probe
        # Monotonic counters (Prometheus + admin info).
        self.connects_total = 0
        self.reconnects_total = 0
        self._conn_attempted = False
        self.rpc_errors_total = 0
        self.breaker_opens_total = 0
        # Called (peer_key) from the reader when a live connection dies
        # — coherence (grid/coherence.py) disarms the peer immediately
        # instead of waiting for its next sync tick.
        self.on_conn_lost: list[Callable[[], None]] = []

    # -- breaker ---------------------------------------------------------

    # A half-open probe that never reports back (its caller's deadline
    # expired mid-call, or an abandoned stream) releases its slot after
    # this long, so one lost probe can never wedge the breaker open
    # against a healthy peer forever.
    PROBE_TTL = 30.0

    def _admit(self) -> None:
        """Fail fast while the breaker is open; let one probe through
        per cooldown window (half-open)."""
        with self._mu:
            if self._open_since == 0.0:
                return
            now = time.monotonic()
            if now - self._open_since < self._open_for:
                raise GridError(
                    f"peer {self.host}:{self.port}: circuit open")
            if self._half_open_probe and \
                    now - self._probe_started < self.PROBE_TTL:
                raise GridError(
                    f"peer {self.host}:{self.port}: circuit half-open, "
                    "probing")
            self._half_open_probe = True
            self._probe_started = now
            self._probe_owner = threading.get_ident()

    def _fault(self) -> None:
        with self._mu:
            self._consecutive += 1
            self.rpc_errors_total += 1
            if self._open_since != 0.0:
                # Failed half-open PROBE: restart the cooldown, doubled
                # (jittered, bounded) — the reconnect backoff. Without
                # the restart every call after the first cooldown would
                # become a probe and eat a connect timeout. Only the
                # probe OWNER's failure counts: stragglers admitted
                # before the breaker opened fault here as their
                # timeouts land, and letting them take this branch
                # would inflate the backoff toward the ceiling and
                # release a live probe's slot mid-flight.
                if not self._half_open_probe or \
                        self._probe_owner != threading.get_ident():
                    return
                self._half_open_probe = False
                self._probe_streak += 1
                self._open_since = time.monotonic()
                self._open_for = min(
                    self.cooldown * (2 ** self._probe_streak),
                    self.cooldown_max) * (0.75 + random.random() / 2)
            elif self._consecutive >= self.trip_after:
                self.breaker_opens_total += 1
                self._open_since = time.monotonic()
                self._probe_streak = 0
                self._open_for = self.cooldown * \
                    (0.75 + random.random() / 2)

    def _ok(self) -> None:
        with self._mu:
            self._consecutive = 0
            self._open_since = 0.0
            self._open_for = 0.0
            self._probe_streak = 0
            self._half_open_probe = False

    def breaker_state(self) -> str:
        with self._mu:
            if self._open_since == 0.0:
                return "closed"
            if time.monotonic() - self._open_since >= self._open_for:
                return "half-open"
            return "open"

    def stats(self) -> dict:
        return {"peer": f"{self.host}:{self.port}",
                "state": self.breaker_state(),
                "connects": self.connects_total,
                "reconnects": self.reconnects_total,
                "rpc_errors": self.rpc_errors_total,
                "breaker_opens": self.breaker_opens_total}

    # -- connection management -----------------------------------------

    def _connect_locked(self) -> None:
        if self._sock is not None:
            return
        # A "reconnect" is any successful connect that was not the
        # client's very first attempt — whether the previous connection
        # died or earlier attempts never got through.
        was_attempted = self._conn_attempted
        self._conn_attempted = True
        try:
            chaos.net("connect")
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.connect_timeout)
        except (OSError, chaos.ChaosInjected) as e:
            raise GridError(f"connect {self.host}:{self.port}: {e}") from None
        s.settimeout(None)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self.connects_total += 1
        if was_attempted:
            self.reconnects_total += 1
        self._last_rx = time.monotonic()
        if wire.native_enabled() and loop.available():
            # Native plane: the process-wide grid poller owns the read
            # side — no reader thread per peer connection.
            loop.poller().register(
                s, on_msg=lambda m: self._on_frame(s, m),
                on_close=lambda: self._drop_conn(s))
        else:
            self._reader = threading.Thread(target=self._read_loop,
                                            args=(s,), daemon=True)
            self._reader.start()

    def _drop_conn(self, s) -> None:
        with self._mu:
            if self._sock is s:
                self._sock = None
            dead = [mux for mux, (sk, _) in self._pending.items() if sk is s]
            pending = [self._pending.pop(mux)[1] for mux in dead]
            credits = [self._credits.pop(mux) for mux in dead
                       if mux in self._credits]
        for q in pending:
            q.put({"t": wire.T_ERR, "e": _SENTINEL_ERR, "msg": "conn lost"})
        for cr in credits:
            cr.close()          # wake push senders parked on credit
        loop.discard(s)
        try:
            s.close()
        except OSError:
            pass
        for cb in self.on_conn_lost:
            try:
                cb()
            except Exception:  # noqa: BLE001 - observers must not break I/O
                pass

    def _on_frame(self, s, msg: dict) -> None:
        """Route one received frame — shared by the poller callback
        (native plane) and the legacy reader thread. Raw bulk frames
        arrive carrying a pooled lease; if no call claims them (the
        stream was abandoned) the lease is released here."""
        self._last_rx = time.monotonic()
        chaos.net("recv")
        t = msg.get("t")
        if t == wire.T_PING:
            with self._wmu:
                with self._mu:
                    live = self._sock is s
                if live:
                    s.sendall(wire.pack_frame({"t": wire.T_PONG}))
            return
        if t == wire.T_PONG:
            return
        if t == wire.T_WIN:
            with self._mu:
                cr = self._credits.get(msg.get("m"))
            if cr is not None:
                cr.grant(msg.get("n", 0))
            return
        ent = self._pending.get(msg.get("m"))
        if ent is not None:
            ent[1].put(msg)
        else:
            lease = msg.get("lease")
            if lease is not None:
                lease.release()

    def _read_loop(self, s) -> None:
        try:
            while True:
                self._on_frame(s, wire.read_frame(s))
        except (GridError, OSError, chaos.ChaosInjected):
            self._drop_conn(s)

    def close(self) -> None:
        with self._mu:
            s, self._sock = self._sock, None
        if s is not None:
            loop.discard(s)
            try:
                s.close()
            except OSError:
                pass

    # -- calls ---------------------------------------------------------

    def _send(self, msg: dict, mux: int, q, tstats=None) -> None:
        if tstats is None:
            frame = wire.pack_frame(msg)
        else:
            # Armed calls time the msgpack encode so the wire span can
            # split serialize out of transit.
            t_ser = time.perf_counter()
            frame = wire.pack_frame(msg)
            tstats["ser"] = time.perf_counter() - t_ser
        with self._mu:
            self._connect_locked()
            s = self._sock
            self._pending[mux] = (s, q)
        try:
            with self._wmu:
                chaos.net("send")
                # Re-check under the write lock: a concurrent failure
                # may have replaced the connection after registration.
                with self._mu:
                    if self._sock is not s:
                        raise OSError("connection replaced")
                s.sendall(frame)
        except (OSError, chaos.ChaosInjected) as e:
            with self._mu:
                self._pending.pop(mux, None)
            # Drop the connection fully (close the socket so the parked
            # reader thread exits, fail other calls in flight on it).
            self._drop_conn(s)
            raise GridError(
                f"send to {self.host}:{self.port}: {e}") from None

    def _finish(self, mux: int) -> None:
        with self._mu:
            self._pending.pop(mux, None)

    def _send_with_retry(self, kind: int, handler: str, payload,
                         window: Optional[int] = None,
                         tc: Optional[dict] = None, tstats=None):
        """Send one request frame, retrying transient connect/send
        failures with jittered exponential backoff. Returns (mux, q).

        Only the SEND phase retries: a frame that failed to leave (or
        a connection that died while it left) was never answered, so
        re-sending cannot double-apply. Retries stop the moment the
        bound request deadline cannot afford another attempt — and the
        moment the breaker opens (a dead peer costs ONE fast failure,
        not a connect timeout per attempt per call)."""
        dl = deadline_mod.current()
        last: Optional[GridError] = None
        for attempt in range(self.send_retries + 1):
            if attempt:
                delay = self.retry_backoff * (2 ** (attempt - 1)) \
                    * (0.5 + random.random())
                if dl is not None and dl.remaining() <= delay:
                    break           # no budget for a backoff: surface
                time.sleep(delay)
            if dl is not None and dl.expired():
                raise DeadlineExceeded(
                    f"deadline exceeded calling {handler} on "
                    f"{self.host}:{self.port}")
            self._admit()
            mux = next(self._mux)
            q: "queue.Queue[dict]" = queue.Queue()
            msg = {"t": kind, "m": mux, "h": handler, "p": payload}
            if window:
                msg["w"] = window
            if tc is not None:
                msg["tc"] = tc
            try:
                self._send(msg, mux, q, tstats)
                return mux, q
            except RemoteCallError:
                raise
            except GridError as e:
                self._fault()
                last = e
        raise last if last is not None else GridError(
            f"send {handler} to {self.host}:{self.port} failed")

    def _rx_live(self, mux: int, window: float) -> bool:
        """True when the call's connection is still the current one
        AND received any frame within `window` seconds — the transport
        is provably alive, so this call's timeout is its own handler's
        problem (slow stream, hung verb), not peer death."""
        with self._mu:
            ent = self._pending.get(mux)
            if ent is None or ent[0] is not self._sock:
                return False
        return (time.monotonic() - self._last_rx) < window

    def _recv(self, q, handler: str, wait: Optional[float],
              mux: Optional[int] = None):
        """One reply frame, waiting at most min(wait, deadline left)."""
        wait = wait or self.call_timeout
        dl = deadline_mod.current()
        eff = wait if dl is None else dl.clamp(wait)
        try:
            return q.get(timeout=eff)
        except queue.Empty:
            if dl is not None and eff < wait:
                # The caller's budget ran out before the peer's window
                # did — the request's problem, never breaker fuel. If
                # THIS thread holds the half-open probe slot, release
                # it (no verdict either way) so the next call can
                # probe; a non-probe call must not release someone
                # else's slot (two concurrent probes would each pay a
                # connect timeout and double the backoff). Probes
                # whose stream is pulled from another thread fall to
                # the PROBE_TTL backstop in _admit.
                with self._mu:
                    if self._half_open_probe and \
                            self._probe_owner == threading.get_ident():
                        self._half_open_probe = False
                raise DeadlineExceeded(
                    f"deadline exceeded awaiting {handler} from "
                    f"{self.host}:{self.port}") from None
            if mux is not None and self._rx_live(mux, max(eff, 1.0)):
                # Per-STREAM failure accounting: other frames are still
                # flowing on the shared connection, so only THIS call
                # failed. Counted, but never breaker fuel — tripping
                # the breaker here would fail the unrelated in-flight
                # streams sharing the socket for one slow handler.
                with self._mu:
                    self.rpc_errors_total += 1
                raise GridError(
                    f"call {handler} to {self.host}:{self.port} timed "
                    "out (connection live)") from None
            self._fault()
            raise GridError(
                f"call {handler} to {self.host}:{self.port} timed out") \
                from None

    def call(self, handler: str, payload=None,
             timeout: Optional[float] = None):
        """Unary call; raises RemoteCallError with the remote's code.

        Disarmed, this path touches no span machinery at all — the one
        `tracing.ACTIVE` attribute check below is its entire tracing
        cost, and the frames it emits carry zero trace bytes."""
        if tracing.ACTIVE and tracing.current() is not None:
            return self._call_traced(handler, payload, timeout)
        mux, q = self._send_with_retry(wire.T_REQ, handler, payload)
        try:
            msg = self._recv(q, handler, timeout, mux)
            if msg["t"] == wire.T_RESP:
                self._ok()
                return msg.get("p")
            code = msg.get("e", "Internal")
            if code == _SENTINEL_ERR:
                self._fault()
                raise GridError("connection lost mid-call")
            # The peer ANSWERED — its handler raised. Healthy
            # transport; never breaker fuel.
            self._ok()
            raise RemoteCallError(code, msg.get("msg", ""))
        finally:
            self._finish(mux)

    @staticmethod
    def _trace_tc(ctx, parent: int) -> dict:
        tc = {"i": ctx.trace_id, "s": parent, "a": 1}
        if tracing.NODE:
            tc["n"] = tracing.NODE
        return tc

    def _stitch(self, ctx, t_wall: float, t0: float, tstats: dict,
                ts: Optional[dict], fault: Optional[str] = None) -> None:
        """Record the explicit `wire` span under the current parent
        (the enclosing grid.<handler> span) and graft the peer's
        shipped subtree into it. `ts` is the reply's piggyback (None
        when the transport faulted — the fault is annotated instead,
        so a partition mid-call still closes the caller's tree)."""
        total_ms = (time.monotonic() - t0) * 1000.0
        ser_ms = round(tstats.get("ser", 0.0) * 1000.0, 3)
        tags = {"peer": f"{self.host}:{self.port}",
                "serialize_ms": ser_ms}
        if fault is not None:
            tags["fault"] = fault
            ts = None
        elif ts:
            q_ms = float(ts.get("q", 0.0))
            v_ms = float(ts.get("v", 0.0))
            tags["peer_queue_ms"] = round(q_ms, 3)
            tags["peer_service_ms"] = round(v_ms, 3)
            tags["transit_ms"] = round(
                max(0.0, total_ms - ser_ms - q_ms - v_ms), 3)
        tracing.stitch_wire(ctx, tracing.current_parent(), t_wall,
                            total_ms, tags, ts)

    def _call_traced(self, handler: str, payload,
                     timeout: Optional[float]):
        """call() under an armed, bound trace context: the request
        frame carries the compact trace context ("tc"), the peer runs
        its handler spans under it and ships the subtree back ("ts"),
        and the reply is stitched into THIS request's tree. A stale
        reply can never stitch: _finish() unregisters the mux before
        this frame's queue is abandoned, and unclaimed frames are
        discarded in _on_frame."""
        ctx = tracing.current()
        with tracing.span("grid", f"grid.{handler}",
                          {"peer": f"{self.host}:{self.port}"}):
            tc = self._trace_tc(ctx, tracing.current_parent())
            tstats: dict = {}
            t_wall = time.time()
            t0 = time.monotonic()
            try:
                mux, q = self._send_with_retry(
                    wire.T_REQ, handler, payload, tc=tc, tstats=tstats)
            except (DeadlineExceeded, GridError) as e:
                self._stitch(ctx, t_wall, t0, tstats, None,
                             fault=type(e).__name__)
                raise
            try:
                try:
                    msg = self._recv(q, handler, timeout, mux)
                except (DeadlineExceeded, GridError) as e:
                    self._stitch(ctx, t_wall, t0, tstats, None,
                                 fault=type(e).__name__)
                    raise
                if msg["t"] == wire.T_RESP:
                    self._stitch(ctx, t_wall, t0, tstats, msg.get("ts"))
                    self._ok()
                    return msg.get("p")
                code = msg.get("e", "Internal")
                if code == _SENTINEL_ERR:
                    self._stitch(ctx, t_wall, t0, tstats, None,
                                 fault="conn_lost")
                    self._fault()
                    raise GridError("connection lost mid-call")
                # The peer ANSWERED — its handler raised. Healthy
                # transport; its spans (up to the raise) still stitch.
                self._stitch(ctx, t_wall, t0, tstats, msg.get("ts"))
                self._ok()
                raise RemoteCallError(code, msg.get("msg", ""))
            finally:
                self._finish(mux)

    def _grant(self, s, mux: int, n: int) -> None:
        """Replenish a response stream's credit window (best-effort:
        a failed grant means the connection is dying and the stream
        will fail through its sentinel)."""
        try:
            frame = wire.pack_frame({"t": wire.T_WIN, "m": mux, "n": n})
            with self._wmu:
                with self._mu:
                    if self._sock is not s:
                        return
                s.sendall(frame)
        except OSError:
            pass

    def stream(self, handler: str, payload=None,
               timeout: Optional[float] = None,
               raw: bool = False) -> Iterator:
        """Streaming call: yields items until EOF. Raises on error.

        On the native plane the open frame advertises a credit window
        and consumed chunks are acknowledged back (T_WIN) as this
        iterator is pulled — a stream nobody drains stalls the SENDER
        after one window instead of ballooning frames into this
        process, and bulk streams can't head-of-line-block lock
        traffic. With raw=True, raw bulk frames are yielded as
        (payload, lease) pairs — payload is a memoryview into a pooled
        buffer and the caller MUST release() the lease (None for a
        v1 peer's plain bytes). With raw=False they are flattened to
        bytes and the lease is released here.

        The span is recorded manually at close (generator `with` would
        leave the thread-local parent pointing into this stream between
        pulls); it covers send through EOF/abandonment, chunk count in
        tags."""
        t_wall = time.time()
        t0 = time.monotonic()
        chunks = 0
        # Armed + bound: the open frame carries the trace context and
        # the peer ships its span subtree back on the EOF/error frame.
        ctx, parent = tracing.capture() if tracing.ACTIVE else (None, 0)
        tc = self._trace_tc(ctx, parent) if ctx is not None else None
        ts: Optional[dict] = None
        fault: Optional[str] = None
        window = loop.stream_window() if wire.native_enabled() else None
        try:
            mux, q = self._send_with_retry(wire.T_SREQ, handler, payload,
                                           window=window, tc=tc)
        except (DeadlineExceeded, GridError) as e:
            if ctx is not None:
                dur = (time.monotonic() - t0) * 1000.0
                sid = tracing.record_span(
                    ctx, parent, "grid", f"grid.{handler}", t_wall, dur,
                    tags={"peer": f"{self.host}:{self.port}",
                          "stream": 1, "chunks": 0})
                tracing.stitch_wire(
                    ctx, sid, t_wall, dur,
                    {"peer": f"{self.host}:{self.port}",
                     "fault": type(e).__name__}, None)
            raise
        with self._mu:
            ent = self._pending.get(mux)
        s = ent[0] if ent is not None else None
        pulled = 0
        try:
            while True:
                msg = self._recv(q, handler, timeout, mux)
                t = msg["t"]
                if t == wire.T_CHUNK:
                    chunks += 1
                    if window:
                        pulled += 1
                        if pulled >= max(1, window // 2):
                            self._grant(s, mux, pulled)
                            pulled = 0
                    lease = msg.get("lease")
                    if msg.get("raw"):
                        if raw:
                            yield msg.get("p"), lease
                        else:
                            p = bytes(msg.get("p") or b"")
                            if lease is not None:
                                lease.release()
                            yield p
                    else:
                        yield msg.get("p")
                elif t == wire.T_EOF:
                    ts = msg.get("ts")
                    self._ok()
                    return
                else:
                    ts = msg.get("ts")
                    code = msg.get("e", "Internal")
                    if code == _SENTINEL_ERR:
                        ts, fault = None, "conn_lost"
                        self._fault()
                        raise GridError("connection lost mid-stream")
                    self._ok()
                    raise RemoteCallError(code, msg.get("msg", ""))
        except RemoteCallError:
            raise               # peer answered: its subtree stitches
        except (DeadlineExceeded, GridError) as e:
            if fault is None:
                ts, fault = None, type(e).__name__
            raise
        finally:
            self._finish(mux)
            if ctx is not None:
                dur = (time.monotonic() - t0) * 1000.0
                peer = f"{self.host}:{self.port}"
                sid = tracing.record_span(
                    ctx, parent, "grid", f"grid.{handler}", t_wall, dur,
                    tags={"peer": peer, "stream": 1, "chunks": chunks})
                wtags = {"peer": peer}
                if fault is not None:
                    wtags["fault"] = fault
                elif ts:
                    q_ms = float(ts.get("q", 0.0))
                    v_ms = float(ts.get("v", 0.0))
                    wtags["peer_queue_ms"] = round(q_ms, 3)
                    wtags["peer_service_ms"] = round(v_ms, 3)
                    wtags["transit_ms"] = round(
                        max(0.0, dur - q_ms - v_ms), 3)
                tracing.stitch_wire(ctx, sid, t_wall, dur, wtags, ts)
            elif tracing.ACTIVE:
                tracing.record(
                    "grid", f"grid.{handler}", t_wall,
                    (time.monotonic() - t0) * 1000.0,
                    tags={"peer": f"{self.host}:{self.port}",
                          "stream": 1, "chunks": chunks})

    def push_raw(self, handler: str, payload, items,
                 timeout: Optional[float] = None):
        """Client-push stream (native plane): open a sink stream, ship
        `items` as raw bulk frames, then await the handler's unary
        result. Items are bytes-like buffers (sliced and sent straight
        off memoryviews, no msgpack wrap) or wire.RawFile descriptors
        (shipped from the file fd via os.sendfile — zero Python-level
        copies send-side). Flow-controlled: the receiver grants credit
        as its handler drains frames, so a slow remote drive stalls
        this sender instead of ballooning its staging queues."""
        window = loop.stream_window()
        stall = loop.stream_stall_s()
        mux, q = self._send_with_retry(wire.T_SREQ, handler, payload,
                                       window=window)
        with self._mu:
            ent = self._pending.get(mux)
            s = ent[0] if ent is not None else None
            credit = loop.Credit(window)
            self._credits[mux] = credit
        try:
            try:
                for item in items:
                    if isinstance(item, wire.RawFile):
                        with open(item.path, "rb") as f:
                            length = item.length
                            if length < 0:
                                length = max(
                                    0, os.fstat(f.fileno()).st_size
                                    - item.offset)
                            loop.send_raw_fd(s, self._wmu, mux,
                                             f.fileno(), item.offset,
                                             length, credit, stall)
                    else:
                        loop.send_raw_buf(s, self._wmu, mux, item,
                                          credit, stall)
                eof = wire.pack_frame({"t": wire.T_EOF, "m": mux})
                with self._wmu:
                    chaos.net("send")
                    s.sendall(eof)
            except (OSError, chaos.ChaosInjected) as e:
                self._drop_conn(s)
                self._fault()
                raise GridError(
                    f"push {handler} to {self.host}:{self.port}: {e}") \
                    from None
            msg = self._recv(q, handler, timeout, mux)
            if msg["t"] == wire.T_RESP:
                self._ok()
                return msg.get("p")
            code = msg.get("e", "Internal")
            if code == _SENTINEL_ERR:
                self._fault()
                raise GridError("connection lost mid-push")
            self._ok()
            raise RemoteCallError(code, msg.get("msg", ""))
        finally:
            self._finish(mux)
            with self._mu:
                self._credits.pop(mux, None)

    def ping(self, timeout: float = 2.0) -> bool:
        try:
            self.call("grid.ping", None, timeout=timeout)
            return True
        except GridError:
            return False


# One client per peer address, shared process-wide (the reference's
# "single connection per node pair").
_clients: dict[tuple[str, int], GridClient] = {}
_clients_mu = threading.Lock()


def client_for(host: str, port: int) -> GridClient:
    key = (host, port)
    with _clients_mu:
        c = _clients.get(key)
        if c is None:
            c = _clients[key] = GridClient(host, port)
        return c


def peer_stats() -> list[dict]:
    """Breaker/counter snapshot of every shared peer client, for the
    Prometheus render and admin info."""
    with _clients_mu:
        clients = list(_clients.values())
    return [c.stats() for c in clients]
