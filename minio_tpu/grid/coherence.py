"""Generation-validated cross-node cache coherence over the grid.

The contract that lets the quorum-fileinfo cache (object/fi_cache) and
the listing/bucket-meta caches stay ON across a distributed deployment
instead of being disabled on remote-drive sets. The old
PeerNotifier.broadcast was fire-and-forget: a dropped invalidation left
a peer serving stale metadata until a TTL — unacceptable for fileinfo,
which has no TTL. This protocol makes invalidation ACKED-OR-ESCALATED
and makes re-arming after any connectivity gap REQUIRE a generation
resync:

  * every node keeps a per-(bucket, class) GENERATION counter, bumped
    BEFORE the invalidation fan-out for each local mutation;
  * invalidations push {node, class, bucket, gen} to every peer and
    wait for acks; a peer that fails to ack is ESCALATED: counted,
    logged at the slow-op channel, and its shared connection reset so
    the failure is surfaced to its next caller instead of festering;
  * each receiver records the highest generation it has APPLIED per
    (peer, bucket, class); a RESYNC pulls a peer's full generation map
    and invalidates every (bucket, class) whose generation advanced
    past the applied record — so invalidations lost while a peer was
    down, partitioned, or restarting are recovered exactly;
  * a peer starts DISARMED and re-arms only after a successful resync;
    any call failure or connection loss to it disarms it again. The
    cache gate `coherent()` is true only with EVERY peer armed —
    gated caches answer misses (never stale hits) the moment the node
    cannot prove it has seen every peer's latest mutation.

Liveness: a periodic sync thread (MTPU_GRID_SYNC_S, default 5 s)
resyncs disarmed peers and pulls armed ones, bounding the staleness
window of an ASYMMETRIC partition (pushes to us fail but our pulls
succeed) to one sync interval; symmetric partitions disarm immediately
via the connection-loss hook or the first failed call.

N x M worker topology (pre-forked workers on a distributed node):
exactly ONE PeerCoherence instance runs per node — in worker 0, the
process that owns the node's grid listener. Sibling workers relay
their outbound bumps to it over loopback (gen.relay) and gate their
caches on the state file it publishes (FileGate); inbound peer
invalidations propagate to siblings through the shared list.gen /
meta.gen bump files io/workers.py already maintains.

Wire surface (registered on the node's GridServer):
    gen.inv    {"n": node, "c": class, "b": bucket, "g": gen} -> "ok"
    gen.sync   {} -> {"n": node, "g": {class: {bucket: gen}}}
    gen.relay  {"b": bucket, "c": class} -> "ok"   (loopback siblings)
"""

from __future__ import annotations

import os
import threading
import time
import uuid as uuid_mod
from typing import Callable, Optional

from minio_tpu.grid.wire import GridError
from minio_tpu.utils import tracing
from minio_tpu.utils.env import env_float as _env_float

# Shared push pool: invalidation fan-outs ride fixed daemon workers
# instead of a fresh thread per peer per mutation (the dsync fan-out
# lesson — thread churn per operation is pathological at production
# mutation rates).
_push_pool = None
_push_pool_mu = threading.Lock()


def _shared_push_pool():
    global _push_pool
    with _push_pool_mu:
        if _push_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _push_pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="coherence-push")
        return _push_pool

INV_HANDLER = "gen.inv"
SYNC_HANDLER = "gen.sync"
RELAY_HANDLER = "gen.relay"

# Invalidation classes. LISTING covers the namespace caches that ride
# the metacache bump funnel (walk streams AND the fileinfo cache);
# BUCKET_META covers bucket configuration (versioning, policies, ...).
CLASS_LISTING = "listing"
CLASS_BUCKET_META = "bucket-meta"
CLASSES = (CLASS_LISTING, CLASS_BUCKET_META)


def make_set_invalidator(sets, layer=None) -> Callable[[str, str], None]:
    """Standard on_invalidate over erasure sets: LISTING drops walk
    streams + fileinfo entries through the bump funnel (bucket "" =
    every known bucket, plus an explicit fi_cache flush for buckets
    cached by GETs that never listed); BUCKET_META drops the TTL
    caches ("" = all). Shared by the server boot and the in-process
    two-node test stacks so the apply semantics cannot drift."""
    def apply_inv(bucket: str, cls: str) -> None:
        if cls == CLASS_BUCKET_META:
            if layer is not None:
                layer.invalidate_bucket_meta(bucket)
            else:
                for es in sets:
                    es.invalidate_bucket_meta(bucket)
            return
        for es in sets:
            mc = es.metacache
            if bucket:
                # broadcast=False: echoing a peer's invalidation back
                # would ping-pong bumps forever.
                mc.bump(bucket, broadcast=False)
                continue
            for b in {k[0] for k in list(mc._walks)} | set(mc._gen):
                mc.bump(b, broadcast=False)
            fc = getattr(es, "fi_cache", None)
            if fc is not None:
                fc.invalidate_all()
        if not bucket:
            # Wildcard invalidation: the hot-object tier caches buckets
            # the bump walk above may never have known (GET-only
            # traffic) — flush every cache in the process explicitly,
            # like the fi_cache flush above.
            from minio_tpu.object import hotcache as _hot
            _hot.flush_process_caches()
    return apply_inv


class FileGate:
    """Sibling-worker view of worker 0's coherence gate (N x M worker
    topology): worker 0 publishes "1"/"0" (coherent or not) to a shared
    state file every sync tick; sibling workers' fi_cache/metacache
    remote gates read it instead of owning a PeerCoherence of their
    own. The rewrite-per-tick doubles as a heartbeat — a stale mtime
    (worker 0 dead or mid-respawn) reads as NOT coherent, so sibling
    caches answer misses during the gap exactly like worker 0's own
    caches do while its peers re-arm."""

    def __init__(self, path: str, ttl: Optional[float] = None,
                 poll: float = 0.05):
        self.path = path
        # Three missed heartbeats = dead publisher; floor keeps slow
        # CI boxes from flapping the gate on scheduler hiccups.
        self.ttl = ttl if ttl is not None else max(
            15.0, 3.0 * _env_float("MTPU_GRID_SYNC_S", 5.0))
        self._poll = poll
        self._at = 0.0
        self._last = False

    def __call__(self) -> bool:
        now = time.monotonic()
        if now - self._at < self._poll:
            return self._last
        self._at = now
        try:
            st = os.stat(self.path)
            with open(self.path, "rb") as f:
                ok = f.read(1) == b"1"
            ok = ok and (time.time() - st.st_mtime) <= self.ttl
        except OSError:
            ok = False
        self._last = ok
        return ok


class PeerCoherence:
    """One node's view of the cluster's cache-invalidation state."""

    def __init__(self, node_id: str, peers: dict,
                 on_invalidate: Optional[Callable[[str, str], None]] = None,
                 sync_interval: Optional[float] = None,
                 ack_timeout: float = 2.0):
        """`peers` maps peer id -> GridClient. `on_invalidate(bucket,
        class)` drops the local caches of that class for that bucket
        ("" = every bucket of the class)."""
        self.node_id = node_id
        self.peers = dict(peers)
        self.on_invalidate = on_invalidate
        self.sync_interval = sync_interval if sync_interval is not None \
            else _env_float("MTPU_GRID_SYNC_S", 5.0)
        self.ack_timeout = ack_timeout
        self._mu = threading.Lock()
        # Boot instance id: generation counters are in-memory and RESET
        # when a node restarts — a peer comparing new (small) gens
        # against pre-restart (large) applied records would see nothing
        # stale and re-arm over missed invalidations. Every inv/sync
        # carries this id; a changed id on a peer means "its counter
        # history is unknowable: flush everything of its classes and
        # start the applied records over" (see resync / handle_inv).
        self.instance_id = str(uuid_mod.uuid4())
        # (class, bucket) -> my generation (bumped per local mutation).
        self._local: dict[tuple, int] = {}
        # peer -> {"i": peer instance id, "gens": {(class, bucket) ->
        # highest generation APPLIED here under that instance}}.
        self._seen: dict[str, dict] = {p: {"i": None, "gens": {}}
                                       for p in peers}
        # peer -> armed. All False until the first resync proves we
        # hold every peer's current generation state.
        self._armed: dict[str, bool] = {p: False for p in peers}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        # N x M worker topology (wired by minio_tpu.server in worker
        # mode): state_path publishes coherent() for sibling FileGates;
        # relay_flag_path is the siblings' dead-man escalation — a
        # sibling whose gen.relay loopback call failed drops the flag,
        # and the next sync tick converts it into a wildcard broadcast.
        self.state_path: Optional[str] = None
        self.relay_flag_path: Optional[str] = None
        # Counters (admin info + Prometheus).
        self.inv_sent = 0
        self.inv_failed = 0
        self.inv_applied = 0
        self.resyncs = 0
        self.escalations = 0
        # Connection-loss hook: a dying connection to a peer disarms it
        # NOW, not at the next sync tick.
        for pid, c in self.peers.items():
            hooks = getattr(c, "on_conn_lost", None)
            if hooks is not None:
                hooks.append(lambda pid=pid: self._disarm(pid))

    # -- gate ----------------------------------------------------------

    def coherent(self) -> bool:
        """True when every peer is armed: the caches this object gates
        may serve hits. One lock-free-ish dict scan — called per cache
        lookup."""
        armed = self._armed
        for v in armed.values():
            if not v:
                return False
        return True

    def armed_count(self) -> int:
        return sum(1 for v in self._armed.values() if v)

    def _disarm(self, peer: str) -> None:
        if self._armed.get(peer):
            self._armed[peer] = False
            self._wake.set()
            # Sibling workers must see the gate drop NOW, not at the
            # next heartbeat — their caches would serve through the gap.
            self._publish_state()

    # -- N x M worker topology (state file + sibling relay) ------------

    def _publish_state(self) -> None:
        path = self.state_path
        if not path:
            return
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write("1" if self.coherent() else "0")
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _check_relay_flag(self) -> None:
        path = self.relay_flag_path
        if not path:
            return
        try:
            os.stat(path)
        except OSError:
            return
        try:
            os.unlink(path)
        except OSError:
            pass
        # A sibling mutated the namespace but could not relay the bump
        # (we were restarting): WHICH bucket is lost with the failed
        # call, so broadcast a wildcard for both classes — peers flush
        # wholesale, exactly what a missed invalidation demands.
        for cls in CLASSES:
            try:
                self.broadcast("", cls)
            except Exception:  # noqa: BLE001 - escalation counted inside
                pass

    def handle_relay(self, payload) -> str:
        """Loopback verb for sibling workers (same node, no grid
        listener of their own): bump + fan out an invalidation on their
        behalf. Their SharedGen bump already covered the node's own
        processes; this covers the peers."""
        p = payload or {}
        self.broadcast(p.get("b", ""), p.get("c", CLASS_LISTING))
        return "ok"

    # -- local mutations -> push ---------------------------------------

    def local_bump(self, bucket: str, cls: str = CLASS_LISTING) -> int:
        with self._mu:
            g = self._local.get((cls, bucket), 0) + 1
            self._local[(cls, bucket)] = g
            return g

    def broadcast(self, bucket: str, cls: str = CLASS_LISTING) -> None:
        """Bump the local generation and push the invalidation to every
        peer, acked-or-escalated. Blocks up to ack_timeout per wave (all
        peers in parallel on the shared push pool) so a mutation's
        response implies reachable peers have already dropped their
        caches."""
        gen = self.local_bump(bucket, cls)
        if not self.peers:
            return
        payload = {"n": self.node_id, "i": self.instance_id,
                   "c": cls, "b": bucket, "g": gen}
        pool = _shared_push_pool()
        futs = [pool.submit(self._push_one, pid, c, payload)
                for pid, c in self.peers.items()]
        deadline = time.monotonic() + self.ack_timeout + 0.5
        for f in futs:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 - outcome counted in push
                pass

    def _push_one(self, pid: str, client, payload) -> None:
        try:
            client.call(INV_HANDLER, payload, timeout=self.ack_timeout)
            with self._mu:
                self.inv_sent += 1
        except Exception as e:  # noqa: BLE001 - escalated below
            self._escalate(pid, client, payload, e)

    def _escalate(self, pid: str, client, payload, err) -> None:
        """A peer did not ack an invalidation. We cannot force a remote
        cache to drop, but the failure is made loud (counted + named on
        the slow-op channel) and the local generation already advanced
        BEFORE the push — so the peer's own periodic resync pull
        applies the missed invalidation within one MTPU_GRID_SYNC_S
        interval, armed or not; that interval is the staleness bound
        for a peer that is up but unreachable from here. No connection
        reset here: the grid client already drops dead connections on
        send failure, and the remaining failure shapes (slow ack,
        remote handler error) have a provably-live transport — closing
        the SHARED client would fail every in-flight storage/lock call
        on it and feed their conn-lost errors to the peer's breaker,
        amplifying one slow ack into a node-level fault."""
        with self._mu:
            self.inv_failed += 1
            self.escalations += 1
        tracing.slow_event(
            "grid", "peer.invalidation-failed",
            tags={"peer": pid, "class": payload.get("c", ""),
                  "bucket": payload.get("b", ""),
                  "error": f"{type(err).__name__}: {err}"})

    # -- receiving side ------------------------------------------------

    def handle_inv(self, payload) -> str:
        p = payload or {}
        node = p.get("n", "")
        instance = p.get("i")
        cls = p.get("c", CLASS_LISTING)
        bucket = p.get("b", "")
        gen = int(p.get("g", 0))
        with self._mu:
            seen = self._seen.setdefault(node, {"i": None, "gens": {}})
            new_instance = instance is not None and seen["i"] != instance
        if new_instance:
            # The peer restarted (new counter history): whatever it
            # invalidated under its previous life is unknowable, and
            # recording the new instance WITHOUT flushing would erase
            # the evidence resync needs to flush later — so flush
            # everything of every class HERE, before the record moves.
            for flush_cls in CLASSES:
                if not self._apply("", flush_cls):
                    raise GridError("invalidation apply failed")
        if not self._apply(bucket, cls):
            # The local drop failed: do NOT record the generation (and
            # do not ack) — the sender escalates, and the next resync
            # retries the invalidation.
            raise GridError("invalidation apply failed")
        with self._mu:
            seen = self._seen.setdefault(node, {"i": None, "gens": {}})
            if instance is not None and seen["i"] != instance:
                seen["i"] = instance
                seen["gens"] = {}
            if gen > seen["gens"].get((cls, bucket), 0):
                seen["gens"][(cls, bucket)] = gen
        return "ok"

    def handle_sync(self, payload) -> dict:
        with self._mu:
            out: dict[str, dict[str, int]] = {}
            for (cls, bucket), gen in self._local.items():
                out.setdefault(cls, {})[bucket] = gen
        return {"n": self.node_id, "i": self.instance_id, "g": out}

    def _apply(self, bucket: str, cls: str) -> bool:
        """Drop the local caches for (bucket, class). Returns success —
        generation records advance only on applied invalidations."""
        cb = self.on_invalidate
        if cb is not None:
            try:
                cb(bucket, cls)
            except Exception:  # noqa: BLE001 - surfaced via return value
                return False
        with self._mu:
            self.inv_applied += 1
        return True

    # -- resync (pull) -------------------------------------------------

    def resync(self, pid: str) -> bool:
        """Pull one peer's generation map, invalidate every (bucket,
        class) whose generation advanced past what we applied, then arm
        the peer. Returns armed."""
        client = self.peers.get(pid)
        if client is None:
            return False
        try:
            remote = client.call(SYNC_HANDLER, {}, timeout=self.ack_timeout)
        except Exception:  # noqa: BLE001 - stays/goes disarmed
            self._armed[pid] = False
            return False
        gens = (remote or {}).get("g", {}) or {}
        instance = (remote or {}).get("i")
        # Key the applied-generation records by the peer's SELF-DECLARED
        # node id — the same key handle_inv records pushes under. Keying
        # by our local handle (pid, endpoint-derived) would split the
        # records whenever a node's bind address differs from the name
        # its peers know it by (e.g. --address 0.0.0.0), making every
        # resync re-apply every invalidation forever.
        declared = (remote or {}).get("n") or pid
        stale: list[tuple[str, str]] = []
        flush_all = False
        with self._mu:
            seen = self._seen.setdefault(declared, {"i": None, "gens": {}})
            if seen["i"] != instance:
                # The peer restarted since we last synced: whatever it
                # invalidated under its PREVIOUS life is unknowable
                # (counters reset, its map may even be empty). The only
                # safe move is a full flush of every class before
                # re-arming over the new instance's history.
                flush_all = True
            else:
                for cls, buckets in gens.items():
                    for bucket, gen in (buckets or {}).items():
                        if int(gen) > seen["gens"].get((cls, bucket), 0):
                            stale.append((bucket, cls))
        if flush_all:
            stale = [("", cls) for cls in CLASSES]
        # Invalidate BEFORE recording the generations and BEFORE
        # arming: a crash between steps re-invalidates (safe), never
        # arms with unapplied generations (unsafe).
        for bucket, cls in stale:
            if not self._apply(bucket, cls):
                self._armed[pid] = False
                return False
        with self._mu:
            seen = self._seen.setdefault(declared, {"i": None, "gens": {}})
            if flush_all:
                seen["i"] = instance
                seen["gens"] = {(cls, bucket): int(gen)
                                for cls, buckets in gens.items()
                                for bucket, gen in (buckets or {}).items()}
            else:
                for (bucket, cls) in stale:
                    g = int((gens.get(cls) or {}).get(bucket, 0))
                    if g > seen["gens"].get((cls, bucket), 0):
                        seen["gens"][(cls, bucket)] = g
            self.resyncs += 1
        self._armed[pid] = True
        return True

    def resync_all(self) -> bool:
        ok = True
        for pid in list(self.peers):
            if not self.resync(pid):
                ok = False
        return ok

    # -- lifecycle -----------------------------------------------------

    def register_into(self, srv) -> None:
        srv.register(INV_HANDLER, self.handle_inv)
        srv.register(SYNC_HANDLER, self.handle_sync)
        srv.register(RELAY_HANDLER, self.handle_relay)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="grid-coherence")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def _loop(self) -> None:
        # First pass immediately: the boot path starts disarmed and
        # should arm as soon as peers answer, not one interval later.
        while not self._stop.is_set():
            try:
                self.resync_all()
            except Exception:  # noqa: BLE001 - keep the daemon alive
                pass
            try:
                self._check_relay_flag()
                self._publish_state()
            except Exception:  # noqa: BLE001 - keep the daemon alive
                pass
            self._wake.wait(self.sync_interval)
            self._wake.clear()

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            return {
                "node": self.node_id,
                "peers": len(self.peers),
                "armed": self.armed_count(),
                "coherent": self.coherent(),
                "inv_sent": self.inv_sent,
                "inv_failed": self.inv_failed,
                "inv_applied": self.inv_applied,
                "resyncs": self.resyncs,
                "escalations": self.escalations,
                "peer_state": {p: bool(a) for p, a in self._armed.items()},
            }
