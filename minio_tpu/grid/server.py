"""Grid server: accepts peer connections, dispatches registered handlers.

The analogue of the reference's grid handler registry + muxServer
(internal/grid/handlers.go:42-101, muxserver.go). Unary handlers return
a msgpack-able payload; stream handlers are generators whose items are
sent as chunk frames. Handler exceptions map to wire error codes via
the registered exception table, so the remote client re-raises the
same storage exception types the local path would see.
"""

from __future__ import annotations

import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from minio_tpu.grid import chaos, wire

# exception class -> wire code (extended by storage/remote.py, dsync).
ERROR_CODES: dict[type, str] = {}


def register_error(exc_type: type, code: str) -> None:
    ERROR_CODES[exc_type] = code


def _code_for(e: Exception) -> str:
    for t in type(e).__mro__:
        if t in ERROR_CODES:
            return ERROR_CODES[t]
    return "Internal"


class GridServer:
    def __init__(self, port: int, host: str = "0.0.0.0",
                 max_workers: int = 32):
        self.host = host
        self.port = port
        self._handlers: dict[str, Callable] = {}
        self._streams: dict[str, Callable] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._conns: set = set()
        self.register("grid.ping", lambda p: "pong")

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def register_stream(self, name: str, fn: Callable) -> None:
        self._streams[name] = fn

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        self._sock = s
        if self.port == 0:
            self.port = s.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            try:
                # shutdown() wakes the thread blocked in accept(); a bare
                # close() would leave the fd (and the LISTEN socket) alive
                # until accept returned.
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for conn in list(self._conns):
            # shutdown() before close(): the per-conn reader thread is
            # blocked in recv, which pins the open socket — a bare
            # close() would neither wake it nor send the FIN, leaving
            # peers parked on a half-dead connection with no signal
            # (their conn-loss hooks — coherence disarm — never fire).
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    # -- per-connection ------------------------------------------------

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()

        def send(msg: dict) -> None:
            blob = wire.pack_frame(msg)
            with wlock:
                chaos.net("send")
                conn.sendall(blob)

        try:
            while True:
                msg = wire.read_frame(conn)
                # Node-level chaos (tests/cluster.py): a blackholed
                # node's server side drops the connection; "drop" mode
                # swallows request frames silently so callers time out
                # (the asymmetric-partition shape).
                chaos.net("recv")
                t = msg.get("t")
                if t in (wire.T_REQ, wire.T_SREQ) and chaos.drop_inbound():
                    continue
                if t == wire.T_PING:
                    send({"t": wire.T_PONG})
                elif t == wire.T_REQ:
                    self._pool.submit(self._run_unary, send, msg)
                elif t == wire.T_SREQ:
                    self._pool.submit(self._run_stream, send, msg)
        except (wire.GridError, OSError, RuntimeError, chaos.ChaosInjected):
            # RuntimeError: pool shut down mid-frame during server stop.
            pass
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _run_unary(self, send, msg: dict) -> None:
        mux = msg.get("m")
        fn = self._handlers.get(msg.get("h", ""))
        try:
            if fn is None:
                send({"t": wire.T_ERR, "m": mux, "e": "NoSuchHandler",
                      "msg": str(msg.get("h"))})
                return
            out = fn(msg.get("p"))
            send({"t": wire.T_RESP, "m": mux, "p": out})
        except Exception as e:  # noqa: BLE001 - mapped onto the wire
            try:
                send({"t": wire.T_ERR, "m": mux, "e": _code_for(e),
                      "msg": str(e)[:512]})
            except OSError:
                pass

    def _run_stream(self, send, msg: dict) -> None:
        mux = msg.get("m")
        fn = self._streams.get(msg.get("h", ""))
        try:
            if fn is None:
                send({"t": wire.T_ERR, "m": mux, "e": "NoSuchHandler",
                      "msg": str(msg.get("h"))})
                return
            for item in fn(msg.get("p")):
                send({"t": wire.T_CHUNK, "m": mux, "p": item})
            send({"t": wire.T_EOF, "m": mux})
        except Exception as e:  # noqa: BLE001 - mapped onto the wire
            try:
                send({"t": wire.T_ERR, "m": mux, "e": _code_for(e),
                      "msg": str(e)[:512]})
            except OSError:
                pass
