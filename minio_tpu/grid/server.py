"""Grid server: accepts peer connections, dispatches registered handlers.

The analogue of the reference's grid handler registry + muxServer
(internal/grid/handlers.go:42-101, muxserver.go). Unary handlers return
a msgpack-able payload; stream handlers are generators whose items are
sent as chunk frames — or wire.RawFile / wire.RawBytes descriptors,
shipped as raw bulk frames (os.sendfile straight from the drive fd for
RawFile: zero Python-level copies send-side). Sink handlers receive a
client-push stream of bulk frames and return one unary result (the
inbound half of the zero-copy shard transfer). Handler exceptions map
to wire error codes via the registered exception table, so the remote
client re-raises the same storage exception types the local path would
see.

On the native plane (MTPU_GRID_NATIVE, grid/wire.py) accepted
connections park on the process-wide grid epoll poller (grid/loop.py)
instead of one blocking reader thread each, and response streams
opened with a credit window ("w" in the open frame) pause after
`window` unacknowledged frames — a bulk walk_scan whose client stopped
draining stalls in its worker slot instead of head-of-line-blocking
lock/coherence traffic or ballooning the receiver's queues.
"""

from __future__ import annotations

import os
import queue as queue_mod
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional

from minio_tpu.grid import chaos, loop, wire
from minio_tpu.utils import tracing

# exception class -> wire code (extended by storage/remote.py, dsync).
ERROR_CODES: dict[type, str] = {}


def register_error(exc_type: type, code: str) -> None:
    ERROR_CODES[exc_type] = code


def _code_for(e: Exception) -> str:
    for t in type(e).__mro__:
        if t in ERROR_CODES:
            return ERROR_CODES[t]
    return "Internal"


class _ConnState:
    """Per-connection server state shared by the frame source (poller
    callback or reader thread) and the handler pool."""

    __slots__ = ("sock", "wlock", "sinks", "credits", "mu")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.wlock = threading.Lock()
        self.mu = threading.Lock()
        # mux -> input queue for a running sink handler
        self.sinks: dict[int, "queue_mod.Queue[dict]"] = {}
        # mux -> Credit for a flow-controlled response stream
        self.credits: dict[int, loop.Credit] = {}

    def send(self, msg: dict) -> None:
        blob = wire.pack_frame(msg)
        with self.wlock:
            chaos.net("send")
            self.sock.sendall(blob)

    def close(self) -> None:
        """Fail everything parked on this connection: sink handlers
        get a conn-lost sentinel, stream senders parked on credit wake
        with failure."""
        with self.mu:
            sinks = list(self.sinks.values())
            credits = list(self.credits.values())
            self.sinks.clear()
            self.credits.clear()
        for q in sinks:
            q.put({"t": wire.T_ERR, "e": "Internal",
                   "msg": "connection lost"})
        for cr in credits:
            cr.close()


class GridServer:
    def __init__(self, port: int, host: str = "0.0.0.0",
                 max_workers: int = 32):
        self.host = host
        self.port = port
        self._handlers: dict[str, Callable] = {}
        self._streams: dict[str, Callable] = {}
        self._sinks: dict[str, Callable] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._conns: dict[socket.socket, _ConnState] = {}
        self.register("grid.ping", lambda p: "pong")

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def register_stream(self, name: str, fn: Callable) -> None:
        self._streams[name] = fn

    def register_sink(self, name: str, fn: Callable) -> None:
        """fn(payload, frames) -> result: `frames` iterates the pushed
        bulk payloads (memoryviews into pooled leases, released as the
        iterator advances); the return value answers the push as one
        unary result."""
        self._sinks[name] = fn

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        self._sock = s
        if self.port == 0:
            self.port = s.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            try:
                # shutdown() wakes the thread blocked in accept(); a bare
                # close() would leave the fd (and the LISTEN socket) alive
                # until accept returned.
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for conn, state in list(self._conns.items()):
            # shutdown() before close(): the per-conn reader thread is
            # blocked in recv, which pins the open socket — a bare
            # close() would neither wake it nor send the FIN, leaving
            # peers parked on a half-dead connection with no signal
            # (their conn-loss hooks — coherence disarm — never fire).
            loop.discard(conn)
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            state.close()
        self._pool.shutdown(wait=False)

    def _accept_loop(self) -> None:
        native = wire.native_enabled() and loop.available()
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            state = _ConnState(conn)
            self._conns[conn] = state
            if native:
                # Native plane: the shared epoll poller owns the read
                # side — no reader thread per accepted connection.
                loop.poller().register(
                    conn,
                    on_msg=lambda m, st=state: self._handle_msg(st, m),
                    on_close=lambda st=state: self._conn_closed(st))
            else:
                threading.Thread(target=self._conn_loop, args=(state,),
                                 daemon=True).start()

    # -- per-connection ------------------------------------------------

    def _conn_closed(self, state: _ConnState) -> None:
        self._conns.pop(state.sock, None)
        state.close()
        try:
            state.sock.close()
        except OSError:
            pass

    def _conn_loop(self, state: _ConnState) -> None:
        conn = state.sock
        try:
            while True:
                self._handle_msg(state, wire.read_frame(conn))
        except (wire.GridError, OSError, RuntimeError, chaos.ChaosInjected):
            # RuntimeError: pool shut down mid-frame during server stop.
            pass
        finally:
            self._conn_closed(state)

    def _handle_msg(self, state: _ConnState, msg: dict) -> None:
        """One inbound frame — shared by the poller callback (native)
        and the legacy reader thread. Raises to drop the connection."""
        # Node-level chaos (tests/cluster.py): a blackholed node's
        # server side drops the connection; "drop" mode swallows
        # request frames silently so callers time out (the
        # asymmetric-partition shape).
        chaos.net("recv")
        t = msg.get("t")
        if t in (wire.T_REQ, wire.T_SREQ) and chaos.drop_inbound():
            return
        if t in (wire.T_REQ, wire.T_SREQ) and "tc" in msg:
            # Armed caller: stamp frame receipt so the reply can report
            # dispatch queue-wait separately from handler service.
            msg["_rx"] = time.monotonic()
        if t == wire.T_PING:
            state.send({"t": wire.T_PONG})
        elif t == wire.T_REQ:
            self._pool.submit(self._run_unary, state.send, msg)
        elif t == wire.T_SREQ:
            if msg.get("h", "") in self._sinks:
                q: "queue_mod.Queue[dict]" = queue_mod.Queue()
                with state.mu:
                    state.sinks[msg.get("m")] = q
                self._pool.submit(self._run_sink, state, msg, q)
            else:
                self._pool.submit(self._run_stream, state, msg)
        elif t == wire.T_WIN:
            with state.mu:
                cr = state.credits.get(msg.get("m"))
            if cr is not None:
                cr.grant(msg.get("n", 0))
        elif t in (wire.T_CHUNK, wire.T_EOF):
            # Client-push frames for a running sink handler.
            with state.mu:
                q = state.sinks.get(msg.get("m"))
            if q is not None:
                q.put(msg)
            else:
                lease = msg.get("lease")
                if lease is not None:
                    lease.release()

    def _run_unary(self, send, msg: dict) -> None:
        mux = msg.get("m")
        fn = self._handlers.get(msg.get("h", ""))
        try:
            if fn is None:
                send({"t": wire.T_ERR, "m": mux, "e": "NoSuchHandler",
                      "msg": str(msg.get("h"))})
                return
            if "tc" not in msg:
                out = fn(msg.get("p"))
                send({"t": wire.T_RESP, "m": mux, "p": out})
                return
            out, ts, err = self._call_traced(fn, msg)
            if err is None:
                send({"t": wire.T_RESP, "m": mux, "p": out, "ts": ts})
            else:
                send({"t": wire.T_ERR, "m": mux, "e": _code_for(err),
                      "msg": str(err)[:512], "ts": ts})
        except Exception as e:  # noqa: BLE001 - mapped onto the wire
            try:
                send({"t": wire.T_ERR, "m": mux, "e": _code_for(e),
                      "msg": str(e)[:512]})
            except OSError:
                pass

    @staticmethod
    def _call_traced(fn, msg: dict):
        """Run a unary handler under the caller's shipped trace context
        ("tc"): the handler's spans (disk.*, engine.*) record into a
        context seeded with the caller's trace id, and the completed
        subtree ships back piggybacked on the reply ("ts") with the
        queue-wait (frame receipt → handler start) / service split.
        Arming is per-call — the context itself is the arm token, held
        for exactly this handler's execution. Returns (out, ts, err)."""
        tc = msg.get("tc") or {}
        rx = msg.get("_rx")
        ctx = tracing.TraceContext(trace_id=str(tc.get("i", "")))
        t_start = time.monotonic()
        q_ms = (t_start - rx) * 1000.0 if rx is not None else 0.0
        out = err = None
        tracing.arm(ctx)
        try:
            with tracing.bind(ctx, 0):
                out = fn(msg.get("p"))
        except Exception as e:  # noqa: BLE001 - shipped as T_ERR
            err = e
        finally:
            tracing.disarm(ctx)
        ts = tracing.export_spans(ctx)
        ts["q"] = round(max(0.0, q_ms), 3)
        ts["v"] = round((time.monotonic() - t_start) * 1000.0, 3)
        if tracing.NODE:
            ts["node"] = tracing.NODE
        return out, ts, err

    # -- response streams ----------------------------------------------

    def _run_stream(self, state: _ConnState, msg: dict) -> None:
        mux = msg.get("m")
        fn = self._streams.get(msg.get("h", ""))
        window = msg.get("w")
        credit: Optional[loop.Credit] = None
        if window:
            credit = loop.Credit(int(window))
            with state.mu:
                state.credits[mux] = credit
        stall = loop.stream_stall_s()
        # Armed caller ("tc" on the open frame): the generator's spans
        # record under the shipped trace context and the subtree ships
        # back on the EOF (or error) frame, same as _call_traced.
        tctx: Optional[tracing.TraceContext] = None
        t_start = q_ms = 0.0
        if "tc" in msg and fn is not None:
            tc = msg.get("tc") or {}
            rx = msg.get("_rx")
            tctx = tracing.TraceContext(trace_id=str(tc.get("i", "")))
            t_start = time.monotonic()
            q_ms = (t_start - rx) * 1000.0 if rx is not None else 0.0
            tracing.arm(tctx)

        def _ts() -> Optional[dict]:
            if tctx is None:
                return None
            ts = tracing.export_spans(tctx)
            ts["q"] = round(max(0.0, q_ms), 3)
            ts["v"] = round((time.monotonic() - t_start) * 1000.0, 3)
            if tracing.NODE:
                ts["node"] = tracing.NODE
            return ts

        try:
            if fn is None:
                state.send({"t": wire.T_ERR, "m": mux,
                            "e": "NoSuchHandler", "msg": str(msg.get("h"))})
                return
            with tracing.bind(tctx, 0):
                for item in fn(msg.get("p")):
                    if isinstance(item, wire.RawFile):
                        self._send_raw_file(state, mux, item, credit,
                                            stall)
                    elif isinstance(item, wire.RawBytes):
                        loop.send_raw_buf(state.sock, state.wlock, mux,
                                          item.data, credit, stall)
                    else:
                        if credit is not None and not credit.take(stall):
                            raise wire.GridError(
                                "stream credit stall "
                                "(receiver not draining)")
                        state.send({"t": wire.T_CHUNK, "m": mux,
                                    "p": item})
            eof = {"t": wire.T_EOF, "m": mux}
            ts = _ts()
            if ts is not None:
                eof["ts"] = ts
            state.send(eof)
        except Exception as e:  # noqa: BLE001 - mapped onto the wire
            try:
                errf = {"t": wire.T_ERR, "m": mux, "e": _code_for(e),
                        "msg": str(e)[:512]}
                ts = _ts()
                if ts is not None:
                    errf["ts"] = ts
                state.send(errf)
            except OSError:
                pass
        finally:
            if tctx is not None:
                tracing.disarm(tctx)
            if credit is not None:
                with state.mu:
                    state.credits.pop(mux, None)

    @staticmethod
    def _send_raw_file(state: _ConnState, mux: int, item: wire.RawFile,
                       credit: Optional[loop.Credit],
                       stall: float) -> None:
        with open(item.path, "rb") as f:
            length = item.length
            if length < 0:
                length = max(0,
                             os.fstat(f.fileno()).st_size - item.offset)
            loop.send_raw_fd(state.sock, state.wlock, mux, f.fileno(),
                             item.offset, length, credit, stall)

    # -- client-push sinks ---------------------------------------------

    def _run_sink(self, state: _ConnState, msg: dict,
                  q: "queue_mod.Queue[dict]") -> None:
        mux = msg.get("m")
        fn = self._sinks[msg.get("h", "")]
        window = int(msg.get("w") or 0)
        stall = loop.stream_stall_s()
        consumed = [0]

        def granted() -> None:
            # Replenish the pusher's window as frames are drained,
            # batched at half a window (best-effort: a failed grant
            # means the connection is dying).
            consumed[0] += 1
            if window and consumed[0] >= max(1, window // 2):
                n, consumed[0] = consumed[0], 0
                try:
                    state.send({"t": wire.T_WIN, "m": mux, "n": n})
                except OSError:
                    pass

        try:
            out = fn(msg.get("p"), self._sink_frames(q, stall, granted))
            state.send({"t": wire.T_RESP, "m": mux, "p": out})
        except Exception as e:  # noqa: BLE001 - mapped onto the wire
            try:
                state.send({"t": wire.T_ERR, "m": mux, "e": _code_for(e),
                            "msg": str(e)[:512]})
            except OSError:
                pass
        finally:
            with state.mu:
                state.sinks.pop(mux, None)
            # Release leases of frames the handler never consumed.
            while True:
                try:
                    m2 = q.get_nowait()
                except queue_mod.Empty:
                    break
                lease = m2.get("lease")
                if lease is not None:
                    lease.release()

    @staticmethod
    def _sink_frames(q: "queue_mod.Queue[dict]", stall: float,
                     granted: Callable[[], None]) -> Iterator:
        """Iterate pushed payloads; each frame's pooled lease is
        released when the consumer advances past it."""
        while True:
            try:
                msg = q.get(timeout=stall)
            except queue_mod.Empty:
                raise wire.GridError(
                    "push stream stalled (sender gone?)") from None
            t = msg.get("t")
            if t == wire.T_EOF:
                return
            if t == wire.T_ERR:
                raise wire.GridError(msg.get("msg", "push stream failed"))
            lease = msg.get("lease")
            try:
                yield msg.get("p")
            finally:
                if lease is not None:
                    lease.release()
            granted()
