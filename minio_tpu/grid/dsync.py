"""dsync: distributed reader/writer quorum locks.

The analogue of the reference's internal/dsync: a DRWMutex acquires the
lock on every node's lock server and succeeds iff a quorum granted it
(write quorum n//2+1, read quorum n - n//2 so the two always overlap —
internal/dsync/drwmutex.go:218-234); held locks refresh continuously
and a refresh-quorum loss invokes the loss callback
(drwmutex.go:256-300). Each node runs a LockServer (the reference's
localLocker, cmd/local-locker.go:63) with TTL-expiring entries so locks
held by a crashed node free themselves.
"""

from __future__ import annotations

import random
import threading
import time
import uuid as uuid_mod
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

from minio_tpu.grid.client import GridClient
from minio_tpu.grid.wire import GridError
from minio_tpu.object.nslock import LockTimeout
from minio_tpu.utils.env import env_float as _env_float


# Holder-liveness window: a SIGKILLed holder's entries expire on every
# surviving lock server within LOCK_TTL of its last refresh, so a
# blocked writer proceeds within that bounded window instead of
# wedging the namespace forever. Refresh must outpace expiry — the
# interval is clamped to TTL/3 so a mis-set pair can never let a
# healthy holder's entries lapse between refreshes.
LOCK_TTL = _env_float("MTPU_GRID_LOCK_TTL", 30.0)
REFRESH_INTERVAL = min(_env_float("MTPU_GRID_LOCK_REFRESH", 8.0),
                       LOCK_TTL / 3.0)

# Shared worker pools and a single refresher servicing every held lock:
# at production concurrency the old thread-per-locker-per-round +
# thread-per-held-lock shape was pathological (round 2/3 advisor
# finding). TWO pools, because refresh tasks BLOCK waiting on their
# fan-out futures: if both ran on one bounded pool, enough held locks
# would occupy every worker with refresh tasks whose nested RPCs could
# never get a thread — all futures time out and every healthy lock
# spuriously reports quorum loss. Fan-out RPCs are leaf tasks on their
# own pool, so they always drain.
_rpc_pool = None
_refresh_pool = None
_pool_mu = threading.Lock()


def _shared_rpc_pool():
    global _rpc_pool
    with _pool_mu:
        if _rpc_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _rpc_pool = ThreadPoolExecutor(max_workers=32,
                                           thread_name_prefix="dsync-rpc")
        return _rpc_pool


def _shared_refresh_pool():
    global _refresh_pool
    with _pool_mu:
        if _refresh_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _refresh_pool = ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="dsync-refresh")
        return _refresh_pool


class _RefreshDaemon:
    """ONE background thread scheduling refreshes for every held
    DRWMutex (instead of one thread per held lock). Individual refresh
    rounds run concurrently on the refresh pool so one slow peer cannot
    starve the other locks' refresh deadlines."""

    _instance = None
    _imu = threading.Lock()

    @classmethod
    def get(cls) -> "_RefreshDaemon":
        with cls._imu:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._mu = threading.Lock()
        self._locks: set = set()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dsync-refreshd")
        self._thread.start()

    def register(self, m: "DRWMutex") -> None:
        with self._mu:
            self._locks.add(m)

    def unregister(self, m: "DRWMutex") -> None:
        with self._mu:
            self._locks.discard(m)

    def _loop(self) -> None:
        while True:
            time.sleep(REFRESH_INTERVAL)
            with self._mu:
                held = list(self._locks)
            for m in held:
                # Dedup in-flight rounds: a slow peer must not let the
                # queue back up past LOCK_TTL (an un-refreshed server
                # entry expires and hands the lock to someone else
                # while this holder still trusts it).
                if not getattr(m, "_refresh_inflight", False):
                    m._refresh_inflight = True
                    try:
                        _shared_refresh_pool().submit(m._refresh_once)
                    except RuntimeError:
                        # Interpreter shutting down: the pool refuses
                        # new futures; the daemon dies with the process.
                        m._refresh_inflight = False
                        return


class LockServer:
    """Per-node lock table with TTL expiry."""

    def __init__(self, ttl: Optional[float] = None):
        self.ttl = ttl if ttl is not None else LOCK_TTL
        self._mu = threading.Lock()
        # resource -> {"writer": uid|None, "wexp": ts,
        #              "readers": {uid: expiry}}
        self._res: dict[str, dict] = {}
        # TTL expirations of entries whose holder stopped refreshing
        # (crashed/SIGKILLed/partitioned) — the liveness counter the
        # lock-leak regression tests assert on.
        self.expired_total = 0

    def _entry(self, resource: str) -> dict:
        e = self._res.get(resource)
        if e is None:
            e = self._res[resource] = {"writer": None, "wexp": 0.0,
                                       "readers": {}}
        return e

    def _expire(self, e: dict, now: float) -> None:
        if e["writer"] is not None and e["wexp"] < now:
            e["writer"] = None
            self.expired_total += 1
        live = {u: x for u, x in e["readers"].items() if x >= now}
        self.expired_total += len(e["readers"]) - len(live)
        e["readers"] = live

    def stats(self) -> dict:
        with self._mu:
            now = time.monotonic()
            writers = sum(1 for e in self._res.values()
                          if e["writer"] is not None and e["wexp"] >= now)
            readers = sum(len(e["readers"]) for e in self._res.values())
            return {"resources": len(self._res), "writers": writers,
                    "readers": readers, "expired_total": self.expired_total,
                    "ttl": self.ttl}

    def try_lock(self, resource: str, uid: str, write: bool) -> bool:
        now = time.monotonic()
        with self._mu:
            e = self._entry(resource)
            self._expire(e, now)
            if write:
                if (e["writer"] in (None, uid)) and not e["readers"]:
                    e["writer"] = uid
                    e["wexp"] = now + self.ttl
                    return True
                return False
            if e["writer"] is None:
                e["readers"][uid] = now + self.ttl
                return True
            return False

    def unlock(self, resource: str, uid: str, write: bool) -> bool:
        with self._mu:
            e = self._res.get(resource)
            if e is None:
                return False
            if write and e["writer"] == uid:
                e["writer"] = None
            else:
                e["readers"].pop(uid, None)
            if e["writer"] is None and not e["readers"]:
                self._res.pop(resource, None)
            return True

    def refresh(self, resource: str, uid: str, write: bool) -> bool:
        now = time.monotonic()
        with self._mu:
            e = self._res.get(resource)
            if e is None:
                return False
            self._expire(e, now)
            if write:
                if e["writer"] != uid:
                    return False
                e["wexp"] = now + self.ttl
                return True
            if uid not in e["readers"]:
                return False
            e["readers"][uid] = now + self.ttl
            return True

    # expose over the grid ---------------------------------------------

    def register_into(self, srv) -> None:
        srv.register("lock.try", lambda p: self.try_lock(p["r"], p["u"],
                                                         p["w"]))
        srv.register("lock.unlock", lambda p: self.unlock(p["r"], p["u"],
                                                          p["w"]))
        srv.register("lock.refresh", lambda p: self.refresh(p["r"], p["u"],
                                                            p["w"]))


class LocalLocker:
    """In-process locker for this node's own LockServer (the reference's
    local fast path, cmd/namespace-lock.go localLockInstance)."""

    def __init__(self, server: LockServer):
        self.server = server

    def try_lock(self, resource, uid, write) -> bool:
        return self.server.try_lock(resource, uid, write)

    def unlock(self, resource, uid, write) -> bool:
        return self.server.unlock(resource, uid, write)

    def refresh(self, resource, uid, write) -> bool:
        return self.server.refresh(resource, uid, write)


class RemoteLocker:
    """Locker on a peer node, reached over the grid."""

    def __init__(self, client: GridClient):
        self.client = client

    def _call(self, op: str, resource: str, uid: str, write: bool):
        """True/False = the peer ANSWERED (vote); None = unreachable
        (breaker open, dead node, partition) — the distinction lets
        DRWMutex fail FAST when a lock quorum cannot possibly form,
        instead of spinning try-rounds against dead peers until its
        timeout."""
        try:
            return bool(self.client.call(
                f"lock.{op}", {"r": resource, "u": uid, "w": write},
                timeout=5.0))
        except GridError:
            return None

    def try_lock(self, resource, uid, write):
        return self._call("try", resource, uid, write)

    def unlock(self, resource, uid, write):
        return self._call("unlock", resource, uid, write)

    def refresh(self, resource, uid, write):
        return self._call("refresh", resource, uid, write)


class DRWMutex:
    """Quorum RW lock over a set of lockers."""

    def __init__(self, lockers: Sequence, resource: str,
                 on_lost: Optional[Callable[[], None]] = None):
        self.lockers = list(lockers)
        self.resource = resource
        self.on_lost = on_lost
        self.uid = str(uuid_mod.uuid4())
        self._write = False
        self._held = False
        # Set when lock() gave up because too few lock servers even
        # ANSWERED to form a quorum (fast-fail path, not contention).
        self.quorum_unreachable = False
        self._stop_refresh = threading.Event()

    def _quorum(self, write: bool) -> int:
        # Read quorum must overlap every possible write quorum:
        # write = n//2 + 1, read = n - n//2 (ceil), so read + write > n
        # for all n (reference: internal/dsync/drwmutex.go:218-234).
        n = len(self.lockers)
        return n // 2 + 1 if write else n - n // 2

    def _fanout(self, op: str, write: bool) -> tuple[int, int, bool]:
        """(granted, reachable, concluded): grants are True votes;
        reachable counts lockers that ANSWERED (True or False) — None
        means the locker could not be reached at all. `concluded` is
        True only when every fan-out task actually RAN to completion
        inside the window: a task still queued behind a saturated
        shared pool proves nothing about its locker, so callers must
        never fast-fail on reachability evidence from an unconcluded
        round."""
        results: list = [None] * len(self.lockers)
        ran = [False] * len(self.lockers)

        def run(i, lk):
            try:
                results[i] = getattr(lk, op)(self.resource, self.uid, write)
            except Exception:  # noqa: BLE001 - dead locker == vote lost
                results[i] = None
            ran[i] = True
        pool = _shared_rpc_pool()
        futs = [pool.submit(run, i, lk)
                for i, lk in enumerate(self.lockers)]
        deadline = time.monotonic() + 6.0
        for f in futs:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 - timeout == vote lost
                pass
        granted = sum(1 for r in results if r is True)
        reachable = sum(1 for r in results if r is not None)
        return granted, reachable, all(ran)

    def lock(self, write: bool = True, timeout: float = 60.0) -> bool:
        # Never spin past the caller's request deadline: the lock
        # attempt is part of a budgeted request (PR-1 deadlines), and
        # a lock that cannot be had inside the budget is a fast 503,
        # not a wedged handler.
        from minio_tpu.utils import deadline as deadline_mod
        dl = deadline_mod.current()
        if dl is not None:
            timeout = min(timeout, max(0.0, dl.remaining()))
        deadline = time.monotonic() + timeout
        quorum = self._quorum(write)
        while True:
            got, reachable, concluded = self._fanout("try_lock", write)
            if got >= quorum:
                self._write = write
                self._held = True
                self._start_refresh()
                return True
            # Failed round: release any partial grants, back off, retry
            # (reference: releaseAll + retry loop, drwmutex.go:218).
            self._fanout("unlock", write)
            if concluded and reachable < quorum:
                # A quorum cannot POSSIBLY form — too many lock
                # servers are dead or partitioned (their breakers make
                # this round microseconds, not connect timeouts).
                # Retrying until the timeout cannot help and wedges
                # every writer for the full window; fail fast and let
                # the client retry against an honest 503.
                self.quorum_unreachable = True
                return False
            if time.monotonic() >= deadline:
                return False
            time.sleep(random.uniform(0.02, 0.1))

    def unlock(self) -> None:
        if not self._held:
            return
        self._held = False
        self._stop_refresh.set()
        _RefreshDaemon.get().unregister(self)
        self._fanout("unlock", self._write)

    def _start_refresh(self) -> None:
        self._stop_refresh.clear()
        _RefreshDaemon.get().register(self)

    def _refresh_once(self) -> None:
        """One refresh round, driven by the shared daemon."""
        try:
            self._refresh_round()
        finally:
            self._refresh_inflight = False

    def _refresh_round(self) -> None:
        if self._stop_refresh.is_set() or not self._held:
            _RefreshDaemon.get().unregister(self)
            return
        granted, _, _ = self._fanout("refresh", self._write)
        if granted < self._quorum(self._write):
            # Quorum lost (network partition, peer restarts): the
            # holder must stop trusting its lock (reference loss
            # callback cancels the op's context).
            self._held = False
            _RefreshDaemon.get().unregister(self)
            if self.on_lost is not None:
                try:
                    self.on_lost()
                except Exception:  # noqa: BLE001
                    pass


class DistNSLock:
    """Namespace-lock interface (see object/nslock.NSLockMap) backed by
    dsync quorum locks — drop-in for ErasureSet.ns in distributed mode
    (reference: distLockInstance, cmd/namespace-lock.go:157)."""

    def __init__(self, lockers: Sequence):
        self.lockers = list(lockers)

    @contextmanager
    def write(self, volume: str, path: str, timeout: float = 60.0):
        m = DRWMutex(self.lockers, f"{volume}/{path}")
        if not m.lock(write=True, timeout=timeout):
            raise LockTimeout(
                f"dist write lock {volume}/{path}"
                + (" (lock quorum unreachable)"
                   if m.quorum_unreachable else ""))
        try:
            yield
        finally:
            m.unlock()

    @contextmanager
    def read(self, volume: str, path: str, timeout: float = 60.0):
        m = DRWMutex(self.lockers, f"{volume}/{path}")
        if not m.lock(write=False, timeout=timeout):
            raise LockTimeout(
                f"dist read lock {volume}/{path}"
                + (" (lock quorum unreachable)"
                   if m.quorum_unreachable else ""))
        try:
            yield
        finally:
            m.unlock()
