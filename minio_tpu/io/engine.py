"""Per-drive submission queues: fixed worker crews, bounded depth.

The analogue of the reference's per-drive connection discipline: every
drive owns a submission queue served by a small fixed crew of workers,
replacing the one shared fan-out ThreadPoolExecutor whose 2n workers
interleaved every request's shard ops across every drive. Properties:

  * bounded depth — a saturated drive sheds new submissions with
    EngineSaturated (per-disk fault isolation in the erasure layer
    turns that into one drive error, counted against quorum) instead
    of queueing unbounded;
  * per-drive ordering pressure — one drive's ops serialize through
    its own crew, so a slow drive convoys only itself, and seek-ish
    interleaving across requests on one drive is bounded by the crew
    size rather than by total concurrency;
  * GIL-friendly workers — the ops the crews run are syscall- and
    native-call-dominated (os I/O, fdatasync, ctypes kernels), which
    all release the GIL; the crews are where the overlap happens;
  * self-cleaning — idle workers exit after IDLE_EXIT_S and respawn on
    demand, so sets created ad hoc (tests, sidecars) do not strand
    threads beyond a short tail.

Environment:
  MTPU_IO_WORKERS  worker crew size per drive (default: 2, dropping to
                   1 when the host has fewer cores than the set has
                   drives — 12 drives x 2 crews on a 2-core box is
                   pure scheduler thrash, and every crew thread's
                   wakeup steals GIL slices from the serve loop)
  MTPU_IO_DEPTH    submission queue depth per drive (default 64)
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time as _time_mod
from concurrent.futures import Future

from minio_tpu.utils.latency import Histogram, LastMinute, summarize

IDLE_EXIT_S = 10.0


class EngineSaturated(Exception):
    """A drive's submission queue is full past the waitable deadline."""


def _env_int(key: str, default: int) -> int:
    try:
        v = int(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


class DriveQueue:
    """One drive's bounded submission queue + worker crew."""

    def __init__(self, name: str, workers: int, depth: int):
        self.name = name
        self.max_workers = max(1, workers)
        self.depth = max(1, depth)
        # SimpleQueue: C-level put/get (queue.Queue's pure-Python
        # Condition costs several GIL-held lock rounds per op — real
        # money at 12 drives x every request). Depth is enforced from
        # qsize(), approximate by one crew's width at worst.
        self._q: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self._mu = threading.Lock()
        self._alive = 0
        self._closed = False
        self.in_flight = 0
        self.submitted_total = 0
        self.rejected_total = 0
        # Always-on per-drive latency attribution: service time (the op
        # on the drive) split from queue wait (time parked behind the
        # crew) — the split that tells a convoyed drive from a slow one.
        # A histogram for all-time Prometheus buckets plus last-minute
        # rings for "is it slow RIGHT NOW" p50/p99/max.
        self.service_hist = Histogram()
        self.service_minute = LastMinute()
        self.wait_minute = LastMinute()

    def submit(self, fn) -> Future:
        """Queue `fn` for this drive; returns its Future. A full queue
        sheds immediately with EngineSaturated — bounded depth, and a
        saturated drive must not stall submissions to healthy ones."""
        f: Future = Future()
        self._enqueue((f, fn, _time_mod.perf_counter()))
        return f

    def submit_nowait(self, fn) -> None:
        """Fire-and-forget submission: `fn` owns its own result/error
        delivery (the erasure fan-out's latch slots). Saves the Future
        allocation + two lock/notify rounds per op on the hot path."""
        self._enqueue((None, fn, _time_mod.perf_counter()))

    def _enqueue(self, item) -> None:
        if self._closed:
            # A post-close submission must fail fast: nobody will ever
            # work the queue, and a silently parked job would hang its
            # fan-out latch forever.
            raise EngineSaturated(f"drive {self.name}: engine closed")
        if self._q.qsize() >= self.depth:
            # Saturated: shed IMMEDIATELY (bounded depth, not
            # unbounded queueing). The erasure layer counts the shed
            # against quorum like any other drive fault; waiting here
            # would stall submission to every healthy drive behind
            # this one in the fan-out loop — the convoy the per-drive
            # queues exist to prevent.
            with self._mu:
                self.rejected_total += 1
            raise EngineSaturated(
                f"drive {self.name}: submission queue full "
                f"({self.depth} deep)")
        self._q.put(item)
        spawn = False
        with self._mu:
            self.submitted_total += 1
            # Spawn a worker when the backlog outruns the live crew
            # (workers idle-exit; the crew regrows on demand).
            if not self._closed and self._alive < self.max_workers \
                    and (self._alive == 0 or not self._q.empty()):
                self._alive += 1
                spawn = True
        if spawn:
            threading.Thread(target=self._work, daemon=True,
                             name=f"io-{self.name}").start()

    def _work(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=IDLE_EXIT_S)
            except queue_mod.Empty:
                with self._mu:
                    # Re-check under the lock: a submit landing between
                    # the timeout and here must not strand its item
                    # with a crew of zero.
                    if self._q.empty() or self._closed:
                        self._alive -= 1
                        return
                continue
            if item is None:
                with self._mu:
                    self._alive -= 1
                return
            f, fn, t_sub = item
            if f is not None and not f.set_running_or_notify_cancel():
                continue
            with self._mu:
                self.in_flight += 1
            t0 = _time_mod.perf_counter()
            try:
                if f is None:
                    fn()        # fire-and-forget: fn delivers its own
                else:
                    f.set_result(fn())
            except BaseException as e:  # noqa: BLE001 - ferried to caller
                if f is not None:
                    f.set_exception(e)
            finally:
                t1 = _time_mod.perf_counter()
                with self._mu:
                    self.in_flight -= 1
                now = _time_mod.time()
                self.service_hist.observe(t1 - t0)
                self.service_minute.observe(t1 - t0, now=now)
                self.wait_minute.observe(t0 - t_sub, now=now)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            alive = self._alive
        for _ in range(alive):
            self._q.put(None)   # busy workers also see _closed at idle

    def stats(self) -> dict:
        with self._mu:
            out = {
                "queued": self._q.qsize(),
                "in_flight": self.in_flight,
                "depth": self.depth,
                "workers": self._alive,
                "submitted_total": self.submitted_total,
                "rejected_total": self.rejected_total,
            }
        out["service_hist"] = self.service_hist.state()
        svc_w = self.service_minute.window()
        wait_w = self.wait_minute.window()
        # Summaries for admin info; raw windows so a sibling worker's
        # scrape can MERGE the fleet's per-drive view (percentiles do
        # not merge from summaries, only from bucket counts).
        out["last_minute"] = summarize(svc_w)
        out["last_minute_wait"] = summarize(wait_w)
        out["last_minute_window"] = svc_w
        out["last_minute_wait_window"] = wait_w
        return out


# -- the accelerator lane ---------------------------------------------------
# The TPU is one shared resource exactly like a drive: concurrent device
# dispatches (several EC configs' stripe batchers, solo device-sized
# windows) contend for the same chip mesh, and uncoordinated submission
# from many request threads interleaves compiles and transfers. One
# process-wide single-worker DriveQueue serializes every device dispatch
# and gives the same wait-vs-service attribution drives get — "is the
# accelerator the wall" reads off the identical stats machinery.

_kernel_lane: DriveQueue | None = None
_kernel_mu = threading.Lock()


def kernel_lane() -> DriveQueue:
    """The process-wide device-dispatch queue (1 worker, deep enough
    that coalesced bursts never shed — a shed dispatch would fail whole
    PUT batches, unlike one drive op counted against quorum)."""
    global _kernel_lane
    if _kernel_lane is None:
        with _kernel_mu:
            if _kernel_lane is None:
                _kernel_lane = DriveQueue("kernel", workers=1, depth=1024)
    return _kernel_lane


class IOEngine:
    """The per-drive queues of one erasure set."""

    def __init__(self, names, workers: int | None = None,
                 depth: int | None = None):
        names = list(names)
        if workers is None:
            default = 2 if (os.cpu_count() or 1) >= len(names) else 1
            workers = _env_int("MTPU_IO_WORKERS", default)
        depth = depth if depth is not None \
            else _env_int("MTPU_IO_DEPTH", 64)
        self.queues = [DriveQueue(str(nm), workers, depth) for nm in names]

    def submit(self, drive_idx: int, fn) -> Future:
        return self.queues[drive_idx].submit(fn)

    def submit_nowait(self, drive_idx: int, fn) -> None:
        self.queues[drive_idx].submit_nowait(fn)

    def close(self) -> None:
        for q in self.queues:
            q.close()

    def stats(self) -> list[dict]:
        return [q.stats() for q in self.queues]
