"""Pre-forked SO_REUSEPORT front-end: N worker processes, one port.

The multi-core escape from the single GIL-shared ThreadingHTTPServer
process (the reference's goroutine-per-request model spreads over all
cores for free; CPython needs processes). Each worker binds the SAME
(host, port) via SO_REUSEPORT — the kernel load-balances accepted
connections across workers, no proxy hop — and runs the full S3Server
handler stack over its own object-layer instance on the shared drives.

Cross-process coordination:
  * namespace locks — each worker's in-process NSLockMap is wrapped
    with striped flock() files under the first drive's system volume
    (FlockNSLock), so put/delete/heal of one key serialize across
    workers exactly as they do across threads;
  * cache invalidation — namespace mutations append to shared
    generation files; workers pull-check them before serving listings
    or trusting their bucket-meta TTL caches (the single-process
    bump/TTL model, made multi-process);
  * admission — MTPU_API_REQUESTS_MAX budgets divide across workers
    (ceil), so the fleet-wide in-flight bound stays what the operator
    configured;
  * control pipes — every worker can ask the parent for a cluster
    snapshot (per-worker in-flight, metrics state, admission, bufpool,
    engine depths), so /minio/v2/metrics and admin info served by ANY
    worker aggregate across ALL of them;
  * lifecycle — parent forwards SIGTERM; workers stop accepting,
    drain in-flight requests (S3Server.stop), and exit; the parent
    reaps and restarts unexpectedly-dead workers (bounded).

MTPU_HTTP_WORKERS: worker count (default = cores; 0/1 = today's
in-process mode, used by tests). Distributed topologies pre-fork the
same way: worker 0 additionally owns the node's grid plane — the grid
listener, lock authority and coherence singleton — and siblings reach
it over loopback (see minio_tpu.server's worker-topology wiring).
"""

from __future__ import annotations

import collections
import errno
import fcntl
import itertools
import json
import os
import signal
import socket
import struct
import sys
import threading
import time
import zlib
from contextlib import contextmanager

_NS_STRIPES = 128
_READY_TIMEOUT = 60.0
_DRAIN_TIMEOUT = 15.0
_MAX_RESPAWNS = 10
# Cross-worker trace streaming: the parent polls every worker's trace
# relay on this cadence while any subscription is live, and buffers at
# most this many entries per subscriber (slow stream clients drop
# oldest, same policy as the in-process broadcaster).
_TRACE_POLL_S = 0.2
_TRACE_BUF = 4000


def worker_count_from_env(env=os.environ) -> int:
    """Resolved MTPU_HTTP_WORKERS: default = cores; 0/1 = in-process."""
    raw = env.get("MTPU_HTTP_WORKERS", "")
    if raw.strip() == "":
        return max(1, os.cpu_count() or 1)
    try:
        return max(1, int(raw))
    except ValueError:
        return 1


def reserve_port(host: str, port: int) -> tuple[socket.socket, int]:
    """Bind (not listen) a SO_REUSEPORT socket to learn/hold the port:
    workers then bind+listen the same address; the non-listening
    reservation never receives connections but keeps the port ours
    between forks."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, port))
    return s, s.getsockname()[1]


# ---------------------------------------------------------------------------
# cross-process locks
# ---------------------------------------------------------------------------

class FlockMutex:
    """One exclusive cross-process lock (bucket-metadata RMW). Also
    excludes threads within a process: flock is per open-file-
    description and every acquire opens its own fd. The fd lives in
    thread-local storage — on the shared instance, one thread's exit
    would unlock/close another thread's acquisition."""

    def __init__(self, path: str):
        self._path = path
        self._tls = threading.local()
        os.makedirs(os.path.dirname(path), exist_ok=True)

    def __enter__(self):
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
        except BaseException:
            os.close(fd)
            raise
        stack = getattr(self._tls, "fds", None)
        if stack is None:
            stack = self._tls.fds = []
        stack.append(fd)
        return self

    def __exit__(self, *exc):
        fd = self._tls.fds.pop()
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
        return False


class FlockNSLock:
    """NSLockMap-compatible namespace locking that ALSO excludes other
    worker processes: the in-process RW lock runs first (cheap, full
    fidelity between threads), then a striped flock file is taken
    SH/EX for the cross-process edge. Stripes bound the lock-file
    population; two keys sharing a stripe only over-serialize, never
    under-serialize."""

    def __init__(self, lock_dir: str, inner=None):
        from minio_tpu.object.nslock import NSLockMap
        self._dir = lock_dir
        os.makedirs(lock_dir, exist_ok=True)
        self._inner = inner if inner is not None else NSLockMap()

    def _stripe(self, volume: str, path: str) -> str:
        h = zlib.crc32(f"{volume}/{path}".encode()) % _NS_STRIPES
        return os.path.join(self._dir, f"ns-{h:03d}.lock")

    @contextmanager
    def _flocked(self, volume: str, path: str, op: int, timeout: float):
        from minio_tpu.object.nslock import LockTimeout
        fd = os.open(self._stripe(volume, path),
                     os.O_CREAT | os.O_RDWR, 0o644)
        deadline = time.monotonic() + timeout
        try:
            while True:
                try:
                    fcntl.flock(fd, op | fcntl.LOCK_NB)
                    break
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                    if time.monotonic() >= deadline:
                        raise LockTimeout(
                            f"cross-worker lock on {volume}/{path}") \
                            from None
                    time.sleep(0.005)
            try:
                yield
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)

    @contextmanager
    def write(self, volume: str, path: str, timeout: float = 30.0):
        with self._inner.write(volume, path, timeout):
            with self._flocked(volume, path, fcntl.LOCK_EX, timeout):
                yield

    @contextmanager
    def read(self, volume: str, path: str, timeout: float = 30.0):
        with self._inner.read(volume, path, timeout):
            with self._flocked(volume, path, fcntl.LOCK_SH, timeout):
                yield


# ---------------------------------------------------------------------------
# shared generation files (pull-model cache invalidation)
# ---------------------------------------------------------------------------

class SharedGen:
    """A monotonic cross-process generation: bump() appends one byte
    (O_APPEND — atomic), changed() compares the observed size against
    the last seen. Size inequality — not ordering — signals change, so
    even truncation/recreation invalidates."""

    def __init__(self, path: str, poll_interval: float = 0.0):
        """poll_interval > 0 rate-limits the stat() behind changed():
        calls inside the window reuse the last verdict (False). Only
        for generations whose consumers tolerate that much staleness —
        bucket-meta config, not the listing/fileinfo generation, whose
        cross-worker read-after-write tests demand stat-per-lookup."""
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._last = -1
        self._poll_interval = poll_interval
        self._polled_at = 0.0

    def bump(self) -> None:
        fd = os.open(self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, b".")
        finally:
            os.close(fd)
        # Our own bump must be visible to our own next changed() only
        # as a NO-change (we made it); more importantly it must not be
        # masked for others — their stat sees the new size. Reset the
        # local window so a bump+read sequence in THIS process observes
        # its own write immediately.
        self._polled_at = 0.0

    def changed(self) -> bool:
        if self._poll_interval > 0.0:
            now = time.monotonic()
            if now - self._polled_at < self._poll_interval:
                return False
            self._polled_at = now
        try:
            size = os.stat(self.path).st_size
        except OSError:
            size = 0
        if size != self._last:
            self._last = size
            return True
        return False


# ---------------------------------------------------------------------------
# control plane (parent <-> workers)
# ---------------------------------------------------------------------------

def _send_msg(sock: socket.socket, obj) -> None:
    blob = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_msg(sock: socket.socket, timeout: float = 5.0):
    sock.settimeout(timeout)
    head = b""
    while len(head) < 4:
        got = sock.recv(4 - len(head))
        if not got:
            raise ConnectionError("control peer closed")
        head += got
    (n,) = struct.unpack(">I", head)
    blob = b""
    while len(blob) < n:
        got = sock.recv(n - len(blob))
        if not got:
            raise ConnectionError("control peer closed")
        blob += got
    return json.loads(blob)


def _drain_stale(sock: socket.socket, grace: float = 0.25) -> None:
    """Best-effort flush after an RPC timeout: a late (possibly
    PARTIAL) reply frame left in the pipe would corrupt framing for
    every later exchange. Give the peer a short grace to finish
    writing, then discard whatever arrived."""
    deadline = time.monotonic() + grace
    try:
        while time.monotonic() < deadline:
            sock.settimeout(max(0.01, deadline - time.monotonic()))
            if not sock.recv(65536):
                return
    except (socket.timeout, OSError):
        pass


def _worker_stat(server, worker_id: int) -> dict:
    """One worker's control-plane snapshot."""
    from minio_tpu.io.bufpool import global_pool
    from minio_tpu.s3.metrics import layer_sets
    engine = []
    fileinfo = []
    for si, s in enumerate(layer_sets(server.object_layer)):
        io_eng = getattr(s, "io", None)
        if io_eng is not None:
            # (set, drive)-labelled so any worker's scrape can merge
            # the FLEET's per-drive latency, not just its own slice.
            engine.extend({"set": si, "drive": di, **st}
                          for di, st in enumerate(io_eng.stats()))
        fic = getattr(s, "fi_cache", None)
        if fic is not None:
            fileinfo.append(fic.stats())
    from minio_tpu.storage import group_commit as _gc_mod
    stat = {
        "worker": worker_id,
        "pid": os.getpid(),
        "in_flight": server._inflight,
        "metrics": server.metrics.state(),
        "admission": server.admission.snapshot(),
        "bufpool": global_pool().stats(),
        "engine": engine,
        "fileinfo_cache": fileinfo,
        # Hot-object read tier: per-worker cache, fleet-merged by the
        # scraping worker (metrics render / admin info).
        "hot_cache": getattr(server, "hot_cache", None)
        and server.hot_cache.stats(),
        # Per-worker group-commit lane occupancy: each worker runs its
        # own lanes, so the fleet view is a merge (group_commit.merge_stats).
        "group_commit": _gc_mod.aggregate_stats(),
    }
    # Event-loop connection plane (s3/eventloop.py): each worker runs
    # its own epoll loop; any worker's metrics/admin scrape merges the
    # fleet's parked/active/shed/loop-lag view from these.
    loop_st = None
    es = getattr(server, "eventloop_stats", None)
    if es is not None:
        try:
            loop_st = es()
        except Exception:  # noqa: BLE001 - snapshot best effort
            loop_st = None
    if loop_st is not None:
        stat["connections"] = loop_st
    # Grid peer breaker state (empty on single-node workers today;
    # carried so a future workers+distributed combination aggregates
    # per-worker peer health for free, like the engine rows above).
    from minio_tpu.grid import client as _grid_client
    gp = _grid_client.peer_stats()
    if gp:
        stat["grid"] = gp
    dh = getattr(server, "drive_heal", None)
    if dh is not None:
        # Bulk heals run on worker 0 only, but SO_REUSEPORT balances
        # metrics/admin scrapes across ALL workers — without this every
        # non-0 worker would answer "no heal running" for (N-1)/N of
        # the scrapes.
        try:
            stat["drive_heal"] = dh.status()
        except Exception:  # noqa: BLE001 - status best effort
            pass
    return stat


class WorkerContext:
    """Everything a forked worker wires into its S3Server."""

    def __init__(self, worker_id: int, total: int,
                 query_sock: socket.socket, hub_sock: socket.socket):
        self.worker_id = worker_id
        self.total = total
        self._query = query_sock       # parent asks US for stats
        self._hub = hub_sock           # we ask parent for cluster stats
        self._hub_mu = threading.Lock()
        self._hub_rid = itertools.count(1)

    def _hub_rpc(self, msg: dict, timeout: float = 5.0) -> dict:
        """rid-tagged request/reply on the hub pipe: a reply landing
        after its request timed out is discarded (or flushed on the
        next timeout) instead of answering the NEXT request — one
        stall must not desynchronize cluster stats / trace
        subscriptions forever."""
        rid = next(self._hub_rid)
        msg = dict(msg)
        msg["rid"] = rid
        with self._hub_mu:
            _send_msg(self._hub, msg)
            deadline = time.monotonic() + timeout
            try:
                while True:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise socket.timeout("hub rpc timeout")
                    reply = _recv_msg(self._hub, timeout=left)
                    if reply.get("rid") == rid:
                        return reply
            except socket.timeout:
                _drain_stale(self._hub)
                raise

    def attach(self, server) -> None:
        """Wire the worker's server: control responder, cluster-stat
        hook, cross-process locks + cache generations, divided
        admission, drain-on-SIGTERM."""
        from minio_tpu.s3.metrics import layer_sets

        server.worker_id = self.worker_id
        server.worker_total = self.total
        server.admission = server.admission.divided(self.total)
        server.cluster_stats = self.cluster_stats
        # Fleet-wide trace subscriptions: the admin trace handler on
        # ANY worker streams every sibling's entries via the parent.
        server.cluster_trace = self

        root = _first_drive_root(server.object_layer)
        if root is not None:
            shared = os.path.join(root, ".mtpu.sys", "workers")
            server.bucket_meta_lock = FlockMutex(
                os.path.join(shared, "bucket-meta.lock"))
            list_gen = SharedGen(os.path.join(shared, "list.gen"))
            meta_gen = SharedGen(os.path.join(shared, "meta.gen"),
                                 poll_interval=0.25)
            for s in layer_sets(server.object_layer):
                _wire_set(s, shared, list_gen, meta_gen)
            # Hot-object tier: each worker holds a private cache, but a
            # sibling's mutation must flush it — observe the same
            # list.gen bump file the fileinfo caches ride. Its OWN
            # SharedGen instance: changed() is stateful per observer.
            hc = getattr(server, "hot_cache", None)
            if hc is not None:
                hc.shared_gen = SharedGen(
                    os.path.join(shared, "list.gen"))

        # Control responder: answer the parent's stat queries.
        threading.Thread(target=self._serve_queries, args=(server,),
                         daemon=True, name="worker-control").start()

        def drain(signum, frame):
            try:
                dh = getattr(server, "drive_heal", None)
                if dh is not None:
                    # Checkpoint any in-flight bulk heal on the way
                    # out (stop() sets the event; bulk_heal_drive
                    # persists its position and returns) so the next
                    # boot resumes instead of rescanning from 'a'.
                    dh.stop()
                server.stop()
                # Graceful drain: stamp local drives so the next boot
                # skips the deep crash-recovery sweep.
                from minio_tpu.storage.local import mark_clean_shutdown
                for s in layer_sets(server.object_layer):
                    for d in s.disks:
                        mark_clean_shutdown(d)
            finally:
                os._exit(0)
        signal.signal(signal.SIGTERM, drain)

    def cluster_stats(self) -> list[dict]:
        """All workers' snapshots, via the parent hub (self included)."""
        return self._hub_rpc({"op": "cluster_stats"}).get("stats", [])

    # -- fleet trace subscriptions (parent pump, see WorkerPool) --------

    def trace_sub(self, types) -> int:
        # Subscribing arms the whole fleet synchronously (the parent
        # drains every worker once before replying), so this can take
        # n_workers x the per-worker rpc budget.
        return self._hub_rpc({"op": "trace_sub", "types": list(types)},
                             timeout=15.0)["sub"]

    def trace_poll(self, sub_id: int) -> list[dict]:
        return self._hub_rpc({"op": "trace_poll", "sub": sub_id}) \
            .get("entries", [])

    def trace_unsub(self, sub_id: int) -> None:
        self._hub_rpc({"op": "trace_unsub", "sub": sub_id})

    def _serve_queries(self, server) -> None:
        while True:
            try:
                msg = _recv_msg(self._query, timeout=3600.0)
            except socket.timeout:
                continue
            except (ConnectionError, OSError):
                return
            op = msg.get("op")
            rid = msg.get("rid")
            try:
                if op == "stat":
                    reply = _worker_stat(server, self.worker_id)
                elif op == "trace_drain":
                    # Each drain re-arms (idempotent) so a respawned
                    # worker starts relaying on the next poll tick
                    # without any extra bookkeeping in the parent.
                    server.tracer.arm_remote(msg.get("types") or ["s3"])
                    reply = {"entries": server.tracer.drain_remote()}
                elif op == "trace_stop":
                    server.tracer.disarm_remote()
                    reply = {"ok": 1}
                else:
                    continue
                if rid is not None:
                    reply["rid"] = rid
                _send_msg(self._query, reply)
            except OSError:
                return


def _first_drive_root(object_layer):
    from minio_tpu.s3.metrics import layer_sets
    for s in layer_sets(object_layer):
        for d in s.disks:
            root = getattr(d, "root", None)
            if root:
                return root
    return None


def _wire_set(s, shared_dir: str, list_gen: SharedGen,
              meta_gen: SharedGen) -> None:
    """One erasure set's cross-worker wiring: flock namespace locks,
    and pull-model invalidation for the listing metacache, the
    bucket-meta TTL caches, and the quorum-fileinfo cache."""
    s.ns = FlockNSLock(os.path.join(shared_dir, "nslocks"), inner=s.ns)

    fi_cache = getattr(s, "fi_cache", None)
    if fi_cache is not None:
        # The fileinfo cache observes the SAME generation file every
        # worker's namespace mutations bump (via the mc.bump wrapper
        # below) — its own SharedGen instance, because changed() is
        # stateful per observer and the metacache already consumes one.
        fi_cache.shared_gen = SharedGen(
            os.path.join(shared_dir, "list.gen"))

    mc = s.metacache
    orig_bump = mc.bump

    def bump(bucket: str, broadcast: bool = True):
        orig_bump(bucket, broadcast)
        list_gen.bump()
    mc.bump = bump

    orig_walk = mc.walk_for

    def walk_for(es, bucket: str, prefix: str, start: str = "", **kw):
        if list_gen.changed():
            # Another worker mutated some namespace since we last
            # looked: orphan EVERY cached walk stream (coarse, but a
            # re-walk is cheap next to serving a listing that misses
            # committed objects). The registry — not just _gen — is
            # the source of bucket names: a worker that never wrote
            # locally has walks but no generation entries.
            buckets = {k[0] for k in list(mc._walks)} | set(mc._gen) \
                | {bucket}
            for b in buckets:
                orig_bump(b, False)
        return orig_walk(es, bucket, prefix, start=start, **kw)
    mc.walk_for = walk_for

    orig_set_meta = s.set_bucket_meta

    def set_bucket_meta(bucket: str, meta: dict):
        orig_set_meta(bucket, meta)
        meta_gen.bump()
    s.set_bucket_meta = set_bucket_meta

    orig_get_meta = s.get_bucket_meta

    def get_bucket_meta(bucket: str):
        if meta_gen.changed():
            s.invalidate_bucket_meta()
        return orig_get_meta(bucket)
    s.get_bucket_meta = get_bucket_meta


# ---------------------------------------------------------------------------
# the pool
# ---------------------------------------------------------------------------

class WorkerPool:
    """Fork + supervise N workers. `boot(address, reuse_port, ctx)`
    runs IN THE CHILD and must build, attach (ctx.attach(server)) and
    START an S3Server bound to `address` with SO_REUSEPORT."""

    def __init__(self, address: str, n_workers: int, boot):
        host, _, port_s = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.n = max(2, n_workers)
        self.boot = boot
        self._reserve, self.port = reserve_port(self.host, int(port_s or 0))
        self.address = f"{self.host}:{self.port}"
        self._children: dict[int, dict] = {}      # pid -> rec
        # Lock-free snapshot of live pids for stop(): the SIGTERM
        # handler runs on the MAIN thread between bytecodes, so it must
        # never take _mu (the same thread may hold it in supervise/
        # _spawn — a non-reentrant self-deadlock). The tuple reference
        # is replaced atomically under _mu and read without it.
        self._pids: tuple = ()
        self._stopping = False
        self._respawns = 0
        self._mu = threading.Lock()
        # Fleet trace subscriptions: sub id -> {types, buf}. While any
        # exist, one pump thread drains every worker's relay and fans
        # entries into each subscriber's bounded buffer.
        self._trace_mu = threading.Lock()
        self._trace_subs: dict[int, dict] = {}
        self._trace_seq = 1
        self._trace_pumping = False
        # Request ids for query-pipe exchanges: a reply that arrives
        # AFTER its request timed out must not be mistaken for the
        # answer to the NEXT request on the same pipe.
        self._rid = itertools.count(1)

    # -- child side ------------------------------------------------------

    def _run_child(self, worker_id: int, query_child, hub_child,
                   respawn: bool) -> None:
        ctx = WorkerContext(worker_id, self.n, query_child, hub_child)
        os.environ["MTPU_HTTP_WORKERS"] = "1"
        os.environ["MTPU_WORKER_ID"] = str(worker_id)
        # Fleet width, visible to the boot path BEFORE maybe_attach_worker
        # runs: distributed N x M topologies shard background ownership
        # (scanner/heal sets) across node_count x worker_count slots.
        os.environ["MTPU_WORKER_TOTAL"] = str(self.n)
        if respawn:
            # A respawned worker 0 boots while siblings are serving:
            # the boot janitor (stale-staging sweep) must NOT run — it
            # would delete their in-flight staged shards.
            os.environ["MTPU_WORKER_RESPAWN"] = "1"
        try:
            self.boot(self.address, True, ctx)
        except BaseException as e:  # noqa: BLE001 - child must not return
            print(f"worker {worker_id} boot failed: {e}", file=sys.stderr)
            os._exit(1)
        while True:
            time.sleep(3600)

    # -- parent side -----------------------------------------------------

    def _spawn(self, worker_id: int, respawn: bool = False) -> None:
        query_parent, query_child = socket.socketpair()
        hub_parent, hub_child = socket.socketpair()
        pid = os.fork()
        if pid == 0:
            query_parent.close()
            hub_parent.close()
            self._reserve.close()
            # The child owns only its own fate: drop the parent's
            # child table so accidental parent-path calls cannot
            # signal siblings.
            self._children = {}
            try:
                self._run_child(worker_id, query_child, hub_child,
                                respawn)
            finally:
                os._exit(0)
        query_child.close()
        hub_child.close()
        rec = {"worker": worker_id, "pid": pid, "query": query_parent,
               "hub": hub_parent, "qmu": threading.Lock()}
        with self._mu:
            self._children[pid] = rec
            self._pids = tuple(self._children)
        threading.Thread(target=self._serve_hub, args=(rec,),
                         daemon=True, name=f"hub-{worker_id}").start()

    def _serve_hub(self, rec) -> None:
        """Answer one child's cluster-stat / trace-subscription
        requests."""
        while True:
            try:
                msg = _recv_msg(rec["hub"], timeout=3600.0)
            except socket.timeout:
                continue
            except (ConnectionError, OSError):
                return
            op = msg.get("op")
            try:
                if op == "cluster_stats":
                    reply = {"stats": self._collect_stats()}
                elif op == "trace_sub":
                    reply = {
                        "sub": self._trace_sub(msg.get("types") or ["s3"])}
                elif op == "trace_poll":
                    reply = {"entries": self._trace_poll(msg.get("sub"))}
                elif op == "trace_unsub":
                    self._trace_unsub(msg.get("sub"))
                    reply = {"ok": 1}
                else:
                    continue
                if msg.get("rid") is not None:
                    reply["rid"] = msg["rid"]
                _send_msg(rec["hub"], reply)
            except OSError:
                return

    # -- fleet trace pump ------------------------------------------------

    def _trace_sub(self, types) -> int:
        now = time.monotonic()
        with self._trace_mu:
            sid = self._trace_seq
            self._trace_seq += 1
            self._trace_subs[sid] = {
                "types": set(types), "t": now,
                "buf": collections.deque(maxlen=_TRACE_BUF)}
            start = not self._trace_pumping
            if start:
                self._trace_pumping = True
        # Arm the fleet SYNCHRONOUSLY before replying: entries for
        # requests issued right after subscribe must not fall into the
        # window before the pump's first tick reaches each worker.
        self._trace_drain_once()
        if start:
            threading.Thread(target=self._trace_pump, daemon=True,
                             name="trace-pump").start()
        return sid

    def _trace_poll(self, sid) -> list[dict]:
        with self._trace_mu:
            sub = self._trace_subs.get(sid)
            if sub is None:
                return []
            sub["t"] = time.monotonic()
            out = list(sub["buf"])
            sub["buf"].clear()
        return out

    def _trace_unsub(self, sid) -> None:
        with self._trace_mu:
            self._trace_subs.pop(sid, None)

    # A live stream handler polls several times per second; one that
    # died without its finally (worker crash, SIGKILL) stops polling —
    # expire it so the fleet disarms instead of pumping forever.
    _TRACE_SUB_TTL = 30.0

    def _trace_drain_once(self) -> None:
        """One drain round over every worker: arms relays with the
        current wanted-type union and fans drained entries into each
        live subscriber's buffer."""
        with self._trace_mu:
            union = set()
            for s in self._trace_subs.values():
                union |= s["types"]
        if not union:
            return
        with self._mu:
            recs = list(self._children.values())
        for rec in recs:
            try:
                reply = self._query_rpc(
                    rec, {"op": "trace_drain",
                          "types": sorted(union)}, timeout=2.0)
            except (OSError, ConnectionError, socket.timeout):
                continue
            entries = reply.get("entries", [])
            if not entries:
                continue
            with self._trace_mu:
                for e in entries:
                    et = e.get("trace_type", "s3")
                    wild = e.get("broadcast", False)
                    for s in self._trace_subs.values():
                        if wild or et in s["types"]:
                            s["buf"].append(e)

    def _trace_pump(self) -> None:
        """Drain every worker's trace relay while subscriptions exist;
        disarm the fleet and exit when the last one goes. Each drain
        message carries the wanted-type union, which doubles as the
        arm signal — respawned workers heal on the next tick."""
        while True:
            now = time.monotonic()
            with self._trace_mu:
                self._trace_subs = {
                    sid: s for sid, s in self._trace_subs.items()
                    if now - s["t"] <= self._TRACE_SUB_TTL}
                if not self._trace_subs:
                    self._trace_pumping = False
                    break
            self._trace_drain_once()
            time.sleep(_TRACE_POLL_S)
        # Last subscriber gone: stop the relays so request paths disarm.
        # Re-check first: a NEW subscription may have started a new pump
        # between our break and here — its workers are (re)arming, and a
        # late trace_stop would disarm them and clear their relay
        # buffers under the new subscriber.
        with self._mu:
            recs = list(self._children.values())
        for rec in recs:
            with self._trace_mu:
                if self._trace_pumping:
                    return          # a successor pump owns arming now
            try:
                self._query_rpc(rec, {"op": "trace_stop"}, timeout=2.0)
            except (OSError, ConnectionError, socket.timeout):
                continue

    def _query_rpc(self, rec, msg: dict, timeout: float) -> dict:
        """One request/reply on a worker's query pipe, rid-tagged:
        stale replies (their request timed out earlier) are discarded
        instead of being served as the answer to THIS request — a
        single timeout must not desynchronize the pipe forever."""
        rid = next(self._rid)
        msg = dict(msg)
        msg["rid"] = rid
        with rec["qmu"]:
            _send_msg(rec["query"], msg)
            deadline = time.monotonic() + timeout
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise socket.timeout(
                        f"worker {rec['worker']} rpc timeout")
                reply = _recv_msg(rec["query"], timeout=left)
                if reply.get("rid") == rid:
                    return reply

    def _collect_stats(self) -> list[dict]:
        out = []
        with self._mu:
            recs = list(self._children.values())
        for rec in sorted(recs, key=lambda r: r["worker"]):
            try:
                reply = self._query_rpc(rec, {"op": "stat"}, timeout=3.0)
                reply.pop("rid", None)
                out.append(reply)
            except (OSError, ConnectionError, socket.timeout):
                out.append({"worker": rec["worker"], "pid": rec["pid"],
                            "unreachable": True})
        return out

    def start(self) -> None:
        """Fork worker 0, wait until it accepts (its boot initializes
        shared on-disk state — formats, system volumes — exactly once),
        then fork the rest."""
        self._spawn(0)
        self._wait_ready()
        for wid in range(1, self.n):
            self._spawn(wid)
        signal.signal(signal.SIGTERM, lambda s, f: self.stop())
        signal.signal(signal.SIGINT, lambda s, f: self.stop())

    def _wait_ready(self) -> None:
        deadline = time.monotonic() + _READY_TIMEOUT
        with self._mu:
            pid0 = next(iter(self._children))
        while time.monotonic() < deadline:
            done, status = os.waitpid(pid0, os.WNOHANG)
            if done:
                # supervise() never sees this pid again; drop it here.
                with self._mu:
                    self._children.pop(pid0, None)
                    self._pids = tuple(self._children)
                raise RuntimeError(
                    f"worker 0 died during boot (status {status})")
            try:
                probe = socket.create_connection(
                    (self.host, self.port), timeout=1.0)
                probe.close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError("worker 0 never started accepting")

    def supervise(self) -> int:
        """Reap children; restart unexpected deaths (bounded); return
        once all children are gone after stop()."""
        while True:
            try:
                pid, status = os.wait()
            except ChildProcessError:
                return 0
            except InterruptedError:
                continue
            with self._mu:
                rec = self._children.pop(pid, None)
                self._pids = tuple(self._children)
            if rec is None:
                continue
            for end in ("query", "hub"):
                try:
                    rec[end].close()
                except OSError:
                    pass
            if self._stopping:
                with self._mu:
                    if not self._children:
                        return 0
                continue
            self._respawns += 1
            if self._respawns > _MAX_RESPAWNS:
                print("too many worker deaths; shutting down",
                      file=sys.stderr)
                self.stop()
                continue
            print(f"worker {rec['worker']} (pid {pid}) died "
                  f"(status {status}); respawning", file=sys.stderr)
            self._spawn(rec["worker"], respawn=True)

    def stop(self) -> None:
        """Graceful drain: SIGTERM every worker (they stop accepting,
        finish in-flight requests, exit); SIGKILL stragglers. SIGNAL-
        SAFE: runs as the SIGTERM/SIGINT handler on the main thread,
        which may be inside a _mu critical section — so this touches
        only the lock-free _pids snapshot; the reaper thread does the
        locked bookkeeping."""
        self._stopping = True
        for pid in self._pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        deadline = time.monotonic() + _DRAIN_TIMEOUT

        def reaper():
            while time.monotonic() < deadline:
                if not self._pids:
                    return
                time.sleep(0.1)
            for pid in self._pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        threading.Thread(target=reaper, daemon=True).start()


def serve_cli(argv, address: str, n_workers: int, main_fn) -> int:
    """CLI glue for `python -m minio_tpu.server`: fork n_workers
    children that each re-enter main_fn with the concrete address and
    MTPU_HTTP_WORKERS=1 (the child's normal single-process boot), the
    parent supervising. main_fn sees MTPU_WORKER_CTX via
    maybe_attach_worker at serve time."""

    def boot(concrete_addr: str, reuse_port: bool, ctx: WorkerContext):
        global _PENDING_CTX
        _PENDING_CTX = ctx
        os.environ["MTPU_REUSE_PORT"] = "1"
        child_argv = _swap_address(argv, address, concrete_addr)
        code = main_fn(child_argv)
        os._exit(code or 0)

    pool = WorkerPool(address, n_workers, boot)
    print(f"minio-tpu pre-forked front-end: {pool.n} workers on "
          f"{pool.address} (SO_REUSEPORT)", flush=True)
    pool.start()
    return pool.supervise()


def _swap_address(argv, old: str, new: str):
    out = list(argv)
    for i, a in enumerate(out):
        if a == old:
            out[i] = new
        elif a.startswith("--address=") and a[len("--address="):] == old:
            out[i] = f"--address={new}"
    if new not in out and not any(a.startswith("--address") for a in out):
        out = ["--address", new] + out
    return out


# Set by serve_cli's child boot before re-entering server main; consumed
# by maybe_attach_worker when the child's S3Server is ready.
_PENDING_CTX: WorkerContext | None = None


def maybe_attach_worker(server) -> None:
    """Called by the server boot just before serving: if this process
    is a pre-forked worker (serve_cli child), wire it up."""
    global _PENDING_CTX
    ctx, _PENDING_CTX = _PENDING_CTX, None
    if ctx is not None:
        ctx.attach(server)
