"""Tiered, reference-counted pool of O_DIRECT-aligned buffers.

The analogue of the reference's internal/bpool byte pools: the PUT
encode+frame output, the GET/heal read staging, and the O_DIRECT
write staging all lease buffers here instead of allocating fresh
numpy/mmap memory per window. At steady state the hot paths allocate
ZERO fresh window buffers (pool hit rate ~100% after warmup — asserted
in tests/test_io_engine.py).

Design:
  * size classes — powers of two from 64 KiB to 64 MiB, each class a
    bounded free list (a request larger than the largest class is
    served unpooled and counted, never refused);
  * leases — a Lease wraps one buffer with a reference count. Writers
    that may outlive the request (a health-wrapped create_file whose
    deadline expired but whose abandoned worker is still writing)
    retain() the lease, so the buffer is never recycled under a live
    reader — the data-corruption mode a plain free list invites;
  * leak accounting — a Lease dropped without release() is returned by
    its finalizer and COUNTED (`leaks`); release() after the refcount
    already hit zero is also counted (`double_releases`) and ignored.
    A dropped lease is returned, never lost.

Alignment: every pooled buffer is backed by mmap pages, so the memory
side of O_DIRECT's alignment contract holds for any pooled view.

Environment:
  MTPU_BUFPOOL_MAX_PER_CLASS  buffers kept per size class (default 16)
  MTPU_BUFPOOL_OFF            "1"/"on" disables pooling (every lease
                              is a fresh buffer; leases still work)
"""

from __future__ import annotations

import mmap
import os
import threading
import weakref

# Size classes: 64 KiB .. 64 MiB, powers of two. Matched to the data
# path's working sizes: shard windows (~128 KiB at EC 8+4), framed
# whole-object outputs (~1.5 MiB per 1 MiB object), streaming encode
# windows (32 MiB) and their framed outputs (48 MiB at EC 8+4).
_MIN_CLASS = 16          # 2**16 = 64 KiB
_MAX_CLASS = 26          # 2**26 = 64 MiB
CLASS_SIZES = tuple(1 << p for p in range(_MIN_CLASS, _MAX_CLASS + 1))


def _class_for(size: int) -> int:
    """Index of the smallest class holding `size`, or -1 if oversized."""
    for i, c in enumerate(CLASS_SIZES):
        if size <= c:
            return i
    return -1


class _LeaseState:
    """Refcount shared by the Lease and its leak finalizer. Lives in a
    separate object because weakref.finalize callbacks run AFTER the
    lease itself is unreachable — the count must survive it."""

    __slots__ = ("mu", "refs")

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self.refs = 1


class Lease:
    """One leased buffer. `view(n)` gives a writable memoryview of the
    first n bytes. Reference-counted: retain() before handing the
    memory to a worker that may outlive you, release() exactly once
    per holder; the buffer returns to the pool when the count hits 0."""

    __slots__ = ("_pool", "_buf", "_cls", "_state", "size", "__weakref__")

    def __init__(self, pool: "BufferPool", buf, cls: int, size: int):
        self._pool = pool
        self._buf = buf
        self._cls = cls
        self._state = _LeaseState()
        self.size = size

    def view(self, n: int | None = None) -> memoryview:
        n = self.size if n is None else n
        if n > len(self._buf):
            raise ValueError(f"lease of {len(self._buf)} cannot view {n}")
        return memoryview(self._buf)[:n]

    @property
    def raw(self):
        """The backing mmap (capacity >= size) for consumers that need
        its file-like API (seek/write) or ctypes.from_buffer. Only
        valid while this holder's reference is live."""
        return self._buf

    def ndarray(self, shape, dtype="uint8"):
        """A numpy view of the leased bytes shaped `shape` (must fit in
        `size`). Only valid while this holder's reference is live — the
        device-batching staging path retains the lease across the whole
        host->HBM dispatch so a recycled buffer can never be rewritten
        under an in-flight transfer."""
        import numpy as _np
        items = int(_np.prod(shape))
        if items * _np.dtype(dtype).itemsize > self.size:
            raise ValueError(f"lease of {self.size} cannot shape {shape}")
        return _np.frombuffer(self._buf, dtype=dtype,
                              count=items).reshape(shape)

    def retain(self) -> "Lease":
        with self._state.mu:
            if self._state.refs <= 0:
                raise ValueError("retain() after final release")
            self._state.refs += 1
        return self

    def release(self) -> None:
        st = self._state
        with st.mu:
            if st.refs <= 0:
                # Double release: counted, never corrupts the free list
                # (returning the same buffer twice would alias two
                # future leases onto one allocation).
                self._pool._count_double_release()
                return
            st.refs -= 1
            done = st.refs == 0
        if done:
            self._pool._return_buf(self._buf, self._cls)

    @property
    def refs(self) -> int:
        with self._state.mu:
            return self._state.refs


class BufferPool:
    """Tiered free lists + lease accounting. Thread-safe."""

    def __init__(self, max_per_class: int | None = None,
                 enabled: bool | None = None):
        if max_per_class is None:
            try:
                max_per_class = int(
                    os.environ.get("MTPU_BUFPOOL_MAX_PER_CLASS", "16"))
            except ValueError:
                max_per_class = 16
        if enabled is None:
            enabled = os.environ.get("MTPU_BUFPOOL_OFF", "").lower() \
                not in ("1", "on", "true")
        self.max_per_class = max(1, max_per_class)
        self.enabled = enabled
        self._mu = threading.Lock()
        self._free: list[list] = [[] for _ in CLASS_SIZES]
        # Stats (all monotonic counters except outstanding/idle_bytes).
        self.hits = 0
        self.misses = 0
        self.oversized = 0
        self.leaks = 0
        self.double_releases = 0
        self.outstanding = 0
        self.idle_bytes = 0

    # -- leasing ---------------------------------------------------------

    def lease(self, size: int) -> Lease:
        """Lease a buffer of at least `size` bytes (pooled when a class
        fits, fresh-and-unpooled otherwise)."""
        cls = _class_for(size) if self.enabled else -1
        buf = None
        if cls >= 0:
            with self._mu:
                if self._free[cls]:
                    buf = self._free[cls].pop()
                    self.hits += 1
                    self.idle_bytes -= len(buf)
                else:
                    self.misses += 1
                self.outstanding += 1
            if buf is None:
                buf = mmap.mmap(-1, CLASS_SIZES[cls])
        else:
            with self._mu:
                self.oversized += 1
                self.outstanding += 1
            buf = mmap.mmap(-1, max(size, mmap.PAGESIZE))
        lease = Lease(self, buf, cls, size)
        # Leak net: a lease dropped with refs still held is returned to
        # the pool by the finalizer and counted. The finalizer holds
        # the shared state + buffer, never the lease itself.
        weakref.finalize(lease, self._finalize_dropped,
                         buf, cls, lease._state)
        return lease

    # -- internals -------------------------------------------------------

    def _count_double_release(self) -> None:
        with self._mu:
            self.double_releases += 1

    def _return_buf(self, buf, cls: int) -> None:
        with self._mu:
            self.outstanding -= 1
            if cls >= 0 and self.enabled \
                    and len(self._free[cls]) < self.max_per_class:
                self._free[cls].append(buf)
                self.idle_bytes += len(buf)
                return
        # Oversized / over-capacity: the mapping dies here.
        try:
            buf.close()
        except (BufferError, ValueError):
            pass          # an exported view still holds it; GC reclaims

    def _finalize_dropped(self, buf, cls: int, state: _LeaseState) -> None:
        """GC found a dropped lease: if refs were still held (the
        leak), zero them, count it, and return the buffer."""
        with state.mu:
            leaked = state.refs > 0
            state.refs = 0
        if leaked:
            with self._mu:
                self.leaks += 1
            self._return_buf(buf, cls)

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            total = self.hits + self.misses
            return {
                "enabled": self.enabled,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "oversized": self.oversized,
                "outstanding": self.outstanding,
                "leaks": self.leaks,
                "double_releases": self.double_releases,
                "idle_bytes": self.idle_bytes,
            }

    def drain(self) -> None:
        """Drop every idle buffer (tests / memory pressure)."""
        with self._mu:
            free, self._free = self._free, [[] for _ in CLASS_SIZES]
            self.idle_bytes = 0
        for lst in free:
            for buf in lst:
                try:
                    buf.close()
                except (BufferError, ValueError):
                    pass


_GLOBAL: BufferPool | None = None
_GLOBAL_MU = threading.Lock()


def global_pool() -> BufferPool:
    """Process-wide pool shared by every set/drive in this process
    (workers are separate processes, so each gets its own)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_MU:
            if _GLOBAL is None:
                _GLOBAL = BufferPool()
    return _GLOBAL
