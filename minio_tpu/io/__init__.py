"""I/O engine: the allocation- and process-level runtime the data path
runs on.

Three pillars (the host-side analogue of the reference's internal/bpool
byte pools + its goroutine-per-connection front-end):

  * bufpool  — tiered, reference-counted pool of O_DIRECT-aligned
               buffers leased by the PUT/GET/heal hot paths instead of
               fresh allocations per window (reference: internal/bpool).
  * engine   — per-drive submission queues with fixed worker crews and
               bounded depth, replacing the shared ad-hoc fan-out pool.
  * workers  — pre-forked SO_REUSEPORT worker processes, each running
               the full S3 handler stack (the multi-core escape from
               the single GIL-shared ThreadingHTTPServer process).
"""

from minio_tpu.io.bufpool import BufferPool, Lease, global_pool  # noqa: F401
