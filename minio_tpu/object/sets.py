"""Multi-set object layer: objects hashed across independent erasure sets.

The analogue of the reference's erasureSets (cmd/erasure-sets.go:51):
a fixed collection of equal-width erasure sets; each object key routes
to exactly one set via SipHash-mod under the deployment id
(cmd/erasure-sets.go:663-701 sipHashMod/getHashedSet), making sets the
embarrassingly-parallel scale-out axis (SURVEY §2.8.3). Bucket
operations fan out to every set; listings merge the per-set sorted
pages.
"""

from __future__ import annotations

import threading
import uuid as uuid_mod
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

from minio_tpu.object.types import (BucketExists, BucketNotEmpty,
                                    BucketNotFound, ListObjectsInfo)
from minio_tpu.utils.siphash import sip_hash_mod


def merge_list_pages(pages: Sequence[ListObjectsInfo],
                     max_keys: int,
                     versioned: bool = False) -> ListObjectsInfo:
    """Merge per-set/per-pool listing pages into one page.

    Each input page is sorted and complete up to its own max_keys, so
    the first max_keys of the merged key order are fully represented.

    Entries dedup by key + VersionID (`versioned` pages keep every
    distinct version; plain pages keep one entry per key): while a
    pool migration is in flight (object/decom.migrate_key) the SAME
    version exists in both the source and the destination pool, and
    pages are merged in pool SEARCH order (destination pools first
    during a drain) — without the dedup a listing taken inside the
    migration window would show the key twice, and "never
    doubly-visible" is the invariant the chaos matrix asserts.
    """
    items: list[tuple[str, str, object]] = []
    seen_prefixes: set[str] = set()
    seen_versions: set[tuple] = set()
    for page in pages:
        for o in page.objects:
            vkey = (o.name, getattr(o, "version_id", "")) if versioned \
                else (o.name,)
            if vkey in seen_versions:
                continue
            seen_versions.add(vkey)
            items.append((o.name, "o", o))
        for p in page.prefixes:
            if p not in seen_prefixes:
                seen_prefixes.add(p)
                items.append((p, "p", p))
    items.sort(key=lambda it: it[0])
    out = ListObjectsInfo()
    truncated_src = any(p.is_truncated for p in pages)
    count = 0
    last = ""
    for name, kind, val in items:
        if count >= max_keys:
            out.is_truncated = True
            break
        if kind == "o":
            out.objects.append(val)
            # Versioned listings carry several entries per key; they
            # count once per entry, matching S3 max-keys semantics.
        else:
            out.prefixes.append(val)
        count += 1
        last = name
    if truncated_src and not out.is_truncated:
        # A source had more keys beyond its page even though the merged
        # page fit: stay truncated so the client keeps paginating.
        out.is_truncated = True
    out.next_marker = last if out.is_truncated else ""
    return out


class ErasureSets:
    """Object layer over N erasure sets of one pool."""

    def __init__(self, sets: Sequence, deployment_id: str = ""):
        self.sets = list(sets)
        self.deployment_id = deployment_id or str(uuid_mod.uuid4())
        self._id_bytes = uuid_mod.UUID(self.deployment_id).bytes
        # Listing fan-out pool (lazy): per-set walk pages run
        # CONCURRENTLY — on distributed sets each page is a round of
        # grid streams, and serializing them multiplies a cluster
        # listing's latency by the set count.
        self._list_pool: Optional[ThreadPoolExecutor] = None
        self._list_pool_mu = threading.Lock()

    # -- routing -------------------------------------------------------

    def set_index(self, object_: str) -> int:
        return sip_hash_mod(object_, len(self.sets), self._id_bytes)

    def set_for(self, object_: str):
        return self.sets[self.set_index(object_)]

    @property
    def disks(self) -> list:
        return [d for s in self.sets for d in s.disks]

    def free_space(self) -> int:
        total = 0
        for s in self.sets:
            for d in s.disks:
                try:
                    total += d.disk_info().free
                except Exception:  # noqa: BLE001 - offline drive
                    pass
        return total

    # -- buckets (fan out to every set) --------------------------------

    def make_bucket(self, bucket: str) -> None:
        errs = []
        for s in self.sets:
            try:
                s.make_bucket(bucket)
            except BucketExists as e:
                errs.append(e)
            # quorum failures propagate: partially-created buckets heal
        if len(errs) == len(self.sets):
            raise BucketExists(bucket)

    def get_bucket_info(self, bucket: str):
        last: Exception = BucketNotFound(bucket)
        for s in self.sets:
            try:
                return s.get_bucket_info(bucket)
            except BucketNotFound as e:
                last = e
        raise last

    def list_buckets(self):
        seen: dict[str, object] = {}
        for s in self.sets:
            try:
                for b in s.list_buckets():
                    if b.name not in seen or b.created < seen[b.name].created:
                        seen[b.name] = b
            except Exception:  # noqa: BLE001 - degraded set tolerated
                continue
        return [seen[k] for k in sorted(seen)]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        # Refuse unless every set's share is empty (unless forced).
        if not force:
            for s in self.sets:
                try:
                    if s.list_objects(bucket, max_keys=1,
                                      include_versions=True).objects:
                        raise BucketNotEmpty(bucket)
                except BucketNotFound:
                    continue
        not_found = 0
        for s in self.sets:
            try:
                s.delete_bucket(bucket, force=force)
            except BucketNotFound:
                not_found += 1
        if not_found == len(self.sets):
            raise BucketNotFound(bucket)

    # -- bucket metadata (replicated to every set) ---------------------

    def get_bucket_meta(self, bucket: str) -> dict:
        for s in self.sets:
            meta = s.get_bucket_meta(bucket)
            if meta:
                return meta
        return {}

    def set_bucket_meta(self, bucket: str, meta: dict) -> None:
        for s in self.sets:
            s.set_bucket_meta(bucket, meta)

    def invalidate_bucket_meta(self, bucket: str = "") -> None:
        for s in self.sets:
            s.invalidate_bucket_meta(bucket)

    def close(self) -> None:
        with self._list_pool_mu:
            if self._list_pool is not None:
                self._list_pool.shutdown(wait=False)
                self._list_pool = None
        for s in self.sets:
            s.close()

    def bucket_versioning(self, bucket: str) -> bool:
        return bool(self.get_bucket_meta(bucket).get("versioning"))

    def set_bucket_versioning(self, bucket: str, status) -> None:
        """status: True/"Enabled", "Suspended", or False (off).
        Suspension is a distinct state (null-versionId writes replace
        the null version; Enabled-era versions survive) — both keys
        are managed here so every caller keeps them consistent."""
        meta = self.get_bucket_meta(bucket)
        meta["versioning"] = status is True or status == "Enabled"
        meta["versioning-suspended"] = status == "Suspended"
        self.set_bucket_meta(bucket, meta)

    # -- objects (route by key) ----------------------------------------

    def put_object(self, bucket, object_, data, opts=None):
        return self.set_for(object_).put_object(bucket, object_, data, opts)

    def get_object(self, bucket, object_, opts=None):
        return self.set_for(object_).get_object(bucket, object_, opts)

    def get_object_stream(self, bucket, object_, opts=None):
        return self.set_for(object_).get_object_stream(bucket, object_, opts)

    def get_object_info(self, bucket, object_, opts=None):
        return self.set_for(object_).get_object_info(bucket, object_, opts)

    def update_object_tags(self, bucket, object_, version_id="", tags=None):
        return self.set_for(object_).update_object_tags(
            bucket, object_, version_id, tags)

    def update_version_metadata(self, bucket, object_, version_id, mutate,
                                allow_delete_marker=False):
        return self.set_for(object_).update_version_metadata(
            bucket, object_, version_id, mutate, allow_delete_marker)

    def delete_object(self, bucket, object_, opts=None):
        return self.set_for(object_).delete_object(bucket, object_, opts)

    def list_versions_all(self, bucket, object_):
        return self.set_for(object_).list_versions_all(bucket, object_)

    # -- multipart (route by key) --------------------------------------

    def new_multipart_upload(self, bucket, object_, opts=None):
        return self.set_for(object_).new_multipart_upload(bucket, object_,
                                                          opts)

    def put_object_part(self, bucket, object_, upload_id, part_number, data,
                        actual_size=None, nonce=""):
        return self.set_for(object_).put_object_part(
            bucket, object_, upload_id, part_number, data,
            actual_size=actual_size, nonce=nonce)

    def get_multipart_upload(self, bucket, object_, upload_id):
        return self.set_for(object_).get_multipart_upload(
            bucket, object_, upload_id)

    def complete_multipart_upload(self, bucket, object_, upload_id, parts):
        return self.set_for(object_).complete_multipart_upload(
            bucket, object_, upload_id, parts)

    def abort_multipart_upload(self, bucket, object_, upload_id):
        return self.set_for(object_).abort_multipart_upload(
            bucket, object_, upload_id)

    def list_parts(self, bucket, object_, upload_id, part_marker=0,
                   max_parts=1000):
        return self.set_for(object_).list_parts(
            bucket, object_, upload_id, part_marker, max_parts)

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for s in self.sets:
            out.extend(s.list_multipart_uploads(bucket, prefix))
        out.sort(key=lambda r: (r.get("object", ""), r.get("initiated", 0)))
        return out

    # -- listing (merge per-set pages) ---------------------------------

    def _listing_pool(self) -> ThreadPoolExecutor:
        with self._list_pool_mu:
            if self._list_pool is None:
                # Sized for several CONCURRENT listings' fan-outs, not
                # one: the pool is shared across requests, and a pool
                # of exactly len(sets) would serialize concurrent
                # listings behind each other — worse than the old
                # sequential-per-request shape once a few requests
                # overlap.
                self._list_pool = ThreadPoolExecutor(
                    max_workers=min(32, 4 * len(self.sets)),
                    thread_name_prefix="sets-list")
            return self._list_pool

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000,
                     include_versions: bool = False) -> ListObjectsInfo:
        def one(s):
            return s.list_objects(
                bucket, prefix=prefix, marker=marker, delimiter=delimiter,
                max_keys=max_keys, include_versions=include_versions)

        if len(self.sets) == 1:
            return merge_list_pages([one(self.sets[0])], max_keys,
                                    versioned=include_versions)
        futs = [self._listing_pool().submit(one, s) for s in self.sets]
        pages = []
        for f in futs:
            try:
                pages.append(f.result())
            except BucketNotFound:
                continue
        if not pages:
            raise BucketNotFound(bucket)
        return merge_list_pages(pages, max_keys,
                                versioned=include_versions)

    # -- healing -------------------------------------------------------

    def heal_object(self, bucket, object_, version_id="", deep=False):
        return self.set_for(object_).heal_object(bucket, object_,
                                                 version_id, deep=deep)

    def heal_bucket(self, bucket):
        out = {"bucket": bucket, "missing": 0, "healed": 0}
        for s in self.sets:
            try:
                r = s.heal_bucket(bucket)
                out["missing"] += r.get("missing", 0)
                out["healed"] += r.get("healed", 0)
            except Exception:  # noqa: BLE001 - set without the bucket
                continue
        return out

    def drain_mrf(self, timeout: float = 10.0) -> None:
        for s in self.sets:
            if s._mrf is not None:
                s.mrf.drain(timeout)
