"""Coherent quorum-fileinfo cache: (bucket, object, version) -> (fi, fis).

Every GET/HEAD pays a k-drive `read_version` fan-out to quorum-pick the
version before a single data byte moves. The reference amortizes that
through its metadata layer; here repeat reads of the same key serve the
quorum-agreed FileInfo (and the per-drive fis the shard-holder map is
built from) straight from memory — zero drive calls — while writes
invalidate, so a cached entry can never outlive the version it
describes.

Coherence model (correctness first, three layers):

  * in-process — every namespace mutation already funnels through
    `MetaCache.bump(bucket)` (puts, deletes, multipart completes,
    heals, decom restores); the erasure set registers this cache as a
    bump listener, so one hook covers every mutation path without
    per-call-site wiring. Invalidation is bucket-wide: coarser than
    per-key, but bump IS the per-mutation signal that already exists
    and a spurious re-read costs one fan-out.
  * insert races — an entry is only stored if the bucket's
    invalidation generation still matches a token taken BEFORE the
    drive fan-out that produced it (`token()`/`put(..., token)`).
    Without this, an unlocked metadata read (get_object_info takes no
    namespace lock) could read pre-overwrite state, lose the race to
    the overwrite's bump, and insert a stale entry nothing would ever
    invalidate.
  * cross-process — pre-forked workers (io/workers.py) attach a
    SharedGen observer on the shared `list.gen` file that every
    worker's bump appends to; `maybe_flush()` runs at each lookup and
    at each token grab, clearing the whole cache when ANY worker
    mutated ANY namespace since we last looked (same pull model the
    listing metacache uses; a full flush is the price of zero
    cross-process chatter on the hot path).

Bounds: entry count AND resident bytes (inline objects carry their
framed shard payloads in fis — a few hundred KiB each at the inline
threshold), both LRU-evicted. Cached entries keep only the k DATA
shards' inline blobs: the GET fast path decodes from those alone,
and the reconstruct path re-reads whatever it needs from the drives
(`resolve_inline` treats the empty not-loaded sentinel as "fetch my
journal"), so parity blobs in the cache would be m/n resident bytes
that no hit ever reads.

HEAD traffic gets its own STAT class: a HEAD needs only the
quorum-agreed fi (no per-drive fis, no inline payloads), so stat
entries live in a separate, much larger LRU — a HEAD storm over
hundreds of thousands of keys fills the stat map without evicting a
single data-class entry the GET fast path depends on, and a stat
entry costs ~1 KB instead of up to an inline payload. Lookups check
the stat map first, then fall through to the data map (a data entry
answers a HEAD for free); inserts from the HEAD path only ever touch
the stat map.

Environment:
  MTPU_FILEINFO_CACHE        "0"/"off" disables the cache entirely
  MTPU_FILEINFO_CACHE_MAX    max cached keys (default 4096)
  MTPU_FILEINFO_CACHE_BYTES  max resident inline bytes (default 256 MiB
                             — sized so a serving box's hot inline
                             working set stays resident; at the 128 KiB
                             shard threshold that is ~250 cached
                             inline objects per process)
  MTPU_FILEINFO_STAT_MAX     max stat-class keys (default 65536)
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Optional


def _env_int(key: str, default: int) -> int:
    try:
        v = int(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


class FileInfoCache:
    """Thread-safe LRU of (bucket, object, version_id) -> (fi, fis)."""

    def __init__(self, max_entries: int | None = None,
                 max_bytes: int | None = None,
                 enabled: bool | None = None,
                 max_stat: int | None = None):
        if enabled is None:
            enabled = os.environ.get("MTPU_FILEINFO_CACHE", "").lower() \
                not in ("0", "off", "false")
        self.enabled = enabled
        self.max_entries = max_entries if max_entries is not None \
            else _env_int("MTPU_FILEINFO_CACHE_MAX", 4096)
        self.max_bytes = max_bytes if max_bytes is not None \
            else _env_int("MTPU_FILEINFO_CACHE_BYTES", 256 << 20)
        self.max_stat = max_stat if max_stat is not None \
            else _env_int("MTPU_FILEINFO_STAT_MAX", 65536)
        self._mu = threading.Lock()
        self._map: OrderedDict = OrderedDict()   # key -> entry dict
        self._stat: OrderedDict = OrderedDict()  # key -> quorum fi only
        self._gens: dict[str, int] = {}          # bucket -> invalidation gen
        self._bytes = 0
        # Cross-process invalidation observer (io/workers.SharedGen or
        # anything with a changed() -> bool); None in single-process.
        self.shared_gen = None
        # Cross-NODE coherence gate (grid/coherence.PeerCoherence
        # .coherent, wired at distributed boot). Remote-drive sets set
        # a deny-all sentinel at construction; the cluster boot
        # replaces it with the live generation protocol — so the cache
        # is ON cluster-wide under the protocol, and a bare remote set
        # without it answers misses, never unprovable hits. None on
        # local-only sets (no gate, no overhead).
        self.remote_gate = None
        # Stats (monotonic counters; entries/bytes are gauges).
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.stat_hits = 0
        self.stat_misses = 0
        self.stat_evictions = 0

    # -- coherence -------------------------------------------------------

    def maybe_flush(self) -> None:
        """Pull-check the cross-process generation; a change made by
        ANY worker flushes everything (pull model, no hot-path IPC)."""
        sg = self.shared_gen
        if sg is not None and sg.changed():
            self.invalidate_all()

    def token(self, bucket: str) -> int:
        """Generation token to take BEFORE the drive fan-out whose
        result will be put(); put() refuses when it no longer
        matches (the read raced a mutation's invalidation).

        setdefault, not get: the bucket must EXIST in the generation
        map from this moment, or an invalidate_all() racing the fan-out
        (a sibling worker's bump seen by maybe_flush) would have no
        entry to bump for it and the stale put() would pass the token
        check."""
        self.maybe_flush()
        with self._mu:
            return self._gens.setdefault(bucket, 0)

    def invalidate_bucket(self, bucket: str) -> None:
        with self._mu:
            self._gens[bucket] = self._gens.get(bucket, 0) + 1
            stale = [k for k in self._map if k[0] == bucket]
            for k in stale:
                self._drop(k)
            sstale = [k for k in self._stat if k[0] == bucket]
            for k in sstale:
                self._stat.pop(k, None)
            if stale or sstale:
                self.invalidations += 1

    def invalidate_all(self) -> None:
        with self._mu:
            for b in set(self._gens) | {k[0] for k in self._map} \
                    | {k[0] for k in self._stat}:
                self._gens[b] = self._gens.get(b, 0) + 1
            if self._map or self._stat:
                self.invalidations += 1
            self._map.clear()
            self._stat.clear()
            self._bytes = 0

    # -- lookup / insert -------------------------------------------------

    def _serving(self) -> bool:
        """May cached entries be SERVED right now? On a distributed
        set this requires the coherence gate: with any peer disarmed
        this node cannot prove it has seen every remote mutation, so
        lookups miss (a re-read fan-out) rather than risk a stale hit.
        Inserts are not gated — the token protocol plus the drop in
        invalidate_bucket make an entry inserted around a resync
        harmless."""
        gate = self.remote_gate
        if gate is None:
            return True
        try:
            return bool(gate())
        except Exception:  # noqa: BLE001 - a broken gate fails closed
            return False

    def get(self, bucket: str, object_: str, version_id: str,
            need_data: bool) -> Optional[tuple]:
        """(fi, fis) or None. `need_data=True` only matches entries
        whose fis were read with read_data (inline payloads loaded) —
        a metadata-only entry must not feed the data path its empty
        inline sentinels."""
        if not self.enabled or not self._serving():
            return None
        self.maybe_flush()
        key = (bucket, object_, version_id)
        with self._mu:
            e = self._map.get(key)
            if e is None or (need_data and not e["read_data"]):
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return e["fi"], e["fis"]

    def put(self, bucket: str, object_: str, version_id: str,
            fi, fis, read_data: bool, token: int) -> None:
        if not self.enabled:
            return
        self.maybe_flush()
        key = (bucket, object_, version_id)
        # Strip parity holders' inline blobs down to the empty
        # not-loaded sentinel (a COPY — the caller's in-flight read may
        # still reconstruct from its own fis). Serving needs the k data
        # shards; a demoted read re-fetches from the drives either way.
        k = fi.erasure.data_blocks if fi is not None else 0
        if k:
            fis = [dataclasses.replace(f, inline_data=b"")
                   if f is not None and f.inline_data
                   and f.erasure.index > k else f
                   for f in fis]
        size = sum(len(f.inline_data) for f in fis
                   if f is not None and f.inline_data)
        with self._mu:
            if self._gens.get(bucket, 0) != token:
                return        # a mutation landed during the fan-out
            old = self._map.get(key)
            if old is not None:
                if old["read_data"] and not read_data:
                    return    # never downgrade a data-bearing entry
                self._drop(key)
            self._map[key] = {"fi": fi, "fis": fis,
                              "read_data": read_data, "bytes": size}
            self._bytes += size
            while len(self._map) > self.max_entries \
                    or self._bytes > self.max_bytes:
                victim = next(iter(self._map))
                self._drop(victim)
                self.evictions += 1

    def _drop(self, key) -> None:
        e = self._map.pop(key, None)
        if e is not None:
            self._bytes -= e["bytes"]

    # -- stat class (HEAD traffic) ---------------------------------------

    def get_stat(self, bucket: str, object_: str, version_id: str):
        """Quorum fi for a HEAD, or None. Checks the stat map first,
        then the data map (either class answers a stat); only the stat
        counters move, so the two classes' hit rates stay separately
        observable."""
        if not self.enabled or not self._serving():
            return None
        self.maybe_flush()
        key = (bucket, object_, version_id)
        with self._mu:
            fi = self._stat.get(key)
            if fi is not None:
                self._stat.move_to_end(key)
                self.stat_hits += 1
                return fi
            e = self._map.get(key)
            if e is not None:
                self.stat_hits += 1
                return e["fi"]
            self.stat_misses += 1
            return None

    def put_stat(self, bucket: str, object_: str, version_id: str,
                 fi, token: int) -> None:
        """Insert a HEAD result into the STAT class only — a HEAD storm
        can never evict data-class entries. Same token protocol as
        put()."""
        if not self.enabled or fi is None:
            return
        self.maybe_flush()
        if fi.inline_data:
            # Defensive: stat entries never carry payload bytes.
            fi = dataclasses.replace(fi, inline_data=b"")
        key = (bucket, object_, version_id)
        with self._mu:
            if self._gens.get(bucket, 0) != token:
                return        # a mutation landed during the fan-out
            self._stat[key] = fi
            self._stat.move_to_end(key)
            while len(self._stat) > self.max_stat:
                # Stat-class trims count separately: the shared
                # evictions counter is documented as DATA-cache thrash
                # pressure, and a big HEAD storm trimming stat entries
                # is healthy, not a thrash signal.
                self._stat.popitem(last=False)
                self.stat_evictions += 1

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            total = self.hits + self.misses
            return {
                "enabled": self.enabled,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "entries": len(self._map),
                "bytes": self._bytes,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "stat_hits": self.stat_hits,
                "stat_misses": self.stat_misses,
                "stat_entries": len(self._stat),
                "stat_evictions": self.stat_evictions,
            }
