"""Background data scanner + heal drivers.

The process that looks at data unprompted — the analogue of the
reference's scanner stack:
  * cmd/data-scanner.go — low-priority cycles over every bucket/object,
    accumulating data-usage statistics and sampling objects for heal
    (1 in healObjectSelectProb=1024 gets a deep, bitrot-verifying pass);
  * cmd/background-newdisks-heal-ops.go — detect replaced/fresh drives
    and bring them back: restore format.json for the slot, then let the
    per-object heals repopulate it;
  * cmd/global-heal.go — a full-set heal sweep (every bucket, every
    object) used by the new-disk flow and the admin heal trigger.

Design: one Scanner owns all erasure sets of the server (pools ->
sets), walks EVERY drive's sorted journal listing per bucket and merges
by key, so presence is known per drive without extra stats; objects
missing anywhere (or hitting the deep-sample counter) route through
heal_object. Usage rolls up per bucket and persists quorum-style to the
system volume so restarts (and the admin API) can read it back.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import threading
import time
from typing import Callable, Optional, Sequence

SYS_VOL = ".mtpu.sys"
USAGE_PATH = "scanner/usage.json"
DEEP_EVERY = 1024     # reference healObjectSelectProb (data-scanner.go:59)


@dataclasses.dataclass
class BucketUsage:
    objects: int = 0
    versions: int = 0
    delete_markers: int = 0
    size: int = 0

    def to_json(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DataUsage:
    """Aggregate usage snapshot (reference: DataUsageInfo)."""
    buckets: dict = dataclasses.field(default_factory=dict)
    objects: int = 0
    versions: int = 0
    delete_markers: int = 0
    total_size: int = 0
    last_update: float = 0.0
    cycles: int = 0
    healed: int = 0
    heal_failures: int = 0

    def to_json(self):
        return {
            "buckets": {b: u.to_json() for b, u in self.buckets.items()},
            "objects": self.objects, "versions": self.versions,
            "delete_markers": self.delete_markers,
            "total_size": self.total_size,
            "last_update": self.last_update, "cycles": self.cycles,
            "healed": self.healed, "heal_failures": self.heal_failures,
        }

    @classmethod
    def from_json(cls, m: dict) -> "DataUsage":
        u = cls()
        for b, bu in (m.get("buckets") or {}).items():
            u.buckets[b] = BucketUsage(**bu)
        for f in ("objects", "versions", "delete_markers", "total_size",
                  "last_update", "cycles", "healed", "heal_failures"):
            setattr(u, f, m.get(f, 0))
        return u


def _walk_all_drives(es, bucket: str, forward_from: str = ""):
    """Merged sorted walk over ALL of the set's drives.

    Yields (path, [(disk_idx, xlmeta_blob), ...]) per key — presence per
    drive falls out of the merge, no extra stat calls. `forward_from`
    resumes the walk at a key (inclusive): checkpointed sweeps — the
    bulk drive heal — restart where they stopped instead of at 'a'."""
    def tagged(i, d):
        try:
            for path, blob in d.walk_dir(bucket, forward_from=forward_from):
                yield path, i, blob
        except Exception:  # noqa: BLE001 - offline drive: contributes nothing
            return

    iters = [tagged(i, d) for i, d in enumerate(es.disks)]
    merged = heapq.merge(*iters, key=lambda t: t[0])
    from itertools import groupby
    for path, grp in groupby(merged, key=lambda t: t[0]):
        yield path, [(i, blob) for _, i, blob in grp]


def walk_bucket_versions(es, bucket: str, forward_from: str = ""):
    """Full-fidelity (path, [FileInfo...]) walk of one set's bucket,
    resumable at a key — the driver for checkpointed background sweeps
    (replication resync).  Each key's versions parse from the first
    readable journal copy; keys with no readable copy are skipped
    (heal owns those)."""
    from minio_tpu.storage.meta import XLMeta
    for path, copies in _walk_all_drives(es, bucket,
                                         forward_from=forward_from):
        for _, blob in copies:
            try:
                versions = XLMeta.load(blob).list_versions(bucket, path)
            except Exception:  # noqa: BLE001 - corrupt journal copy
                continue
            if versions:
                yield path, versions
            break


def scan_set_bucket(es, bucket: str, usage: BucketUsage, state: dict,
                    heal: bool = True, throttle: float = 0.0,
                    on_object: Optional[Callable] = None) -> None:
    """One scanner pass over one bucket of one set: usage accounting,
    missing-shard detection, deep-heal sampling.

    Journal decoding rides the batched native summary scanner
    (storage/meta_scan.BlobScanner): keys accumulate into one pooled
    lease and decode in one GIL-free native call per batch instead of
    a full msgpack + XLMeta build per key — at 10M objects the
    interpreter time was the scanner's whole budget (ROADMAP item 4
    remainder). The full parser runs only for keys the scanner rejects
    or whose versions carry metadata beyond the captured set (the
    hooks need full fidelity there); both are counted in the shared
    minio_tpu_meta_scan_blobs_total{path=fallback} funnel, so the
    scanner's blobs show up in the same coverage metric listings use.
    """
    from minio_tpu.object.healing import heal_bucket, heal_object
    from minio_tpu.storage.meta import XLMeta
    from minio_tpu.storage.meta_scan import BlobScanner, summary_sufficient

    if heal:
        try:
            # Recreate the bucket volume on drives that miss it (fresh /
            # replaced disks) so they participate in the object heals.
            heal_bucket(es, bucket)
        except Exception:  # noqa: BLE001 - bucket gone everywhere
            return

    n = len(es.disks)
    alive = set()
    for i, d in enumerate(es.disks):
        try:
            d.stat_vol(bucket)
            alive.add(i)
        except Exception:  # noqa: BLE001 - offline or missing bucket
            continue

    def full_versions(path, copies):
        """Full-fidelity stack from the first parseable copy (the blob
        the BlobScanner carries back IS copies[0]'s bytes, so the
        copies list alone covers every candidate). None = nothing
        parseable anywhere."""
        for _, b in copies:
            try:
                return XLMeta.load(b).list_versions(bucket, path)
            except Exception:  # noqa: BLE001 - corrupt journal copy
                continue
        return None

    def handle(path, copies, vlist, blob):
        """Account + hook + heal one scanned key (post-flush)."""
        del blob
        if vlist is not None and (on_object is None
                                  or summary_sufficient(vlist)):
            # The listing stream's own trimmed-entry rebuild: scanner
            # hooks (ILM, replication resync) see FileInfos
            # field-identical to a full parse. Only summary-SUFFICIENT
            # keys take this path when hooks exist — their versions
            # carry no metadata beyond etag/content-type/tags by
            # construction, so tier/lock/replication-status probes
            # answer absent exactly as a full parse would.
            versions = es._entry_fileinfos(bucket, path, ("s", vlist))
        else:
            # Summary rejected, or a hook needs metadata the summary
            # does not carry: full parse (the counted fallback already
            # fired for rejected blobs inside the BlobScanner).
            versions = full_versions(path, copies)
        if versions is None:
            return
        # An EMPTY version stack still accounts and heals (a crash
        # mid-delete can leave zero-version journals on some drives —
        # the old per-key loop healed those too); only the hooks need
        # actual versions.
        usage.objects += 1
        usage.versions += len(versions)
        for v in versions:
            if v.deleted:
                usage.delete_markers += 1
            else:
                usage.size += v.size
        if on_object is not None and versions:
            try:
                on_object(bucket, path, versions)
            except Exception:  # noqa: BLE001 - hooks never stop the scan
                pass
        if not heal:
            return
        state["counter"] = state.get("counter", 0) + 1
        present = {i for i, _ in copies}
        missing = alive - present
        deep = state["counter"] % state.get("deep_every", DEEP_EVERY) == 0
        if missing or deep:
            try:
                heal_object(es, bucket, path, deep=deep)
                state["healed"] = state.get("healed", 0) + 1
            except Exception:  # noqa: BLE001 - next cycle retries
                state["failures"] = state.get("failures", 0) + 1
        if throttle:
            time.sleep(throttle)

    bs = BlobScanner()
    batch: list[tuple] = []          # (path, copies) in add order
    try:
        for path, copies in _walk_all_drives(es, bucket):
            bs.add_bytes(path, copies[0][1])
            batch.append((path, copies))
            if bs.full():
                for (path, copies), (_p, vlist, blob) in \
                        zip(batch, bs.flush()):
                    handle(path, copies, vlist, blob)
                batch = []
        for (path, copies), (_p, vlist, blob) in zip(batch, bs.flush()):
            handle(path, copies, vlist, blob)
    finally:
        bs.close()


def check_drive_formats(sets: Sequence, set_size: int = 0) -> int:
    """Runtime new-disk detection (reference:
    cmd/background-newdisks-heal-ops.go:563): a drive whose format.json
    vanished (replaced disk) gets its slot identity restored from a
    healthy peer's layout; the object heals then repopulate it via the
    normal scan. Returns the number of formats restored.

    Self-locating across pools: each pool has its own format layout, so
    the set's row in `layout.sets` comes from where the DONOR drive's
    own UUID sits, never from a global set index (which would cross
    pool boundaries)."""
    from minio_tpu.topology.format import FormatInfo

    healed = 0
    for es in sets:
        layout = None
        donor_pos = None          # (row, column) of the donor in its layout
        fresh: list[int] = []
        donor_q = None
        for q, d in enumerate(es.disks):
            try:
                layout_m = d.read_format()   # None = fresh (no format.json)
            except Exception:  # noqa: BLE001 - offline: neither fresh nor donor
                continue
            if layout_m is None:
                fresh.append(q)
                continue
            if layout is not None:
                continue
            try:
                cand = FormatInfo.from_json(layout_m)
            except Exception:  # noqa: BLE001 - corrupt format: skip
                continue
            for r, row in enumerate(cand.sets):
                if cand.this in row:
                    layout, donor_pos, donor_q = cand, (r, row.index(
                        cand.this)), q
                    break
        if not fresh or layout is None or donor_pos is None:
            continue
        row = layout.sets[donor_pos[0]]
        # The donor's column must line up with its position in es.disks
        # for positional identity restore to be sound.
        if donor_pos[1] != donor_q or len(row) != len(es.disks):
            continue
        for q in fresh:
            d = es.disks[q]
            try:
                fi = FormatInfo(deployment_id=layout.deployment_id,
                                sets=layout.sets, this=row[q])
                d.write_format(fi.to_json())
                healed += 1
            except Exception:  # noqa: BLE001 - still dead: next cycle
                continue
            # A replaced drive misses every object committed before the
            # swap: mark it healing so the drive lifecycle manager
            # (object/drive_heal) owns bringing it back with a
            # checkpointed bulk heal. Best effort — without the marker
            # the per-object scanner heals still converge, just without
            # resume/progress.
            try:
                from minio_tpu.object.drive_heal import mark_healing
                mark_healing(d, donor_pos[0], q,
                             getattr(d, "endpoint", ""))
            except Exception:  # noqa: BLE001 - marker is an optimization
                pass
    return healed


def heal_set(es, deep: bool = False) -> dict:
    """Global heal sweep of one erasure set (reference:
    cmd/global-heal.go:49 healErasureSet): every bucket volume, then
    every object, through the standard heal path."""
    from minio_tpu.object.healing import heal_bucket, heal_object

    stats = {"buckets": 0, "objects": 0, "healed": 0, "failures": 0}
    for b in es.list_buckets():
        try:
            heal_bucket(es, b.name)
            stats["buckets"] += 1
        except Exception:  # noqa: BLE001
            stats["failures"] += 1
        for path, _ in _walk_all_drives(es, b.name):
            stats["objects"] += 1
            try:
                r = heal_object(es, b.name, path, deep=deep)
                if r.healed:
                    stats["healed"] += 1
            except Exception:  # noqa: BLE001
                stats["failures"] += 1
    return stats


class Scanner:
    """The background walker: cycles over all sets at low priority.

    interval: seconds between full cycles; throttle: sleep per scanned
    object (the low-priority knob; reference scannerSleeper). on_object
    hooks receive (bucket, path, versions) per scanned object — the ILM
    evaluator registers here."""

    def __init__(self, sets: Sequence, set_size: int = 0,
                 interval: float = 60.0, throttle: float = 0.001,
                 deep_every: int = DEEP_EVERY):
        self.sets = list(sets)
        self.set_size = set_size or (len(self.sets[0].disks)
                                     if self.sets else 0)
        self.interval = interval
        self.throttle = throttle
        self.deep_every = deep_every
        self.usage = DataUsage()
        self.on_object: list[Callable] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._load_usage()

    # -- persistence ----------------------------------------------------

    def _load_usage(self) -> None:
        for es in self.sets:
            for d in es.disks:
                try:
                    blob = d.read_all(SYS_VOL, USAGE_PATH)
                    self.usage = DataUsage.from_json(json.loads(blob))
                    return
                except Exception:  # noqa: BLE001 - try next drive
                    continue

    def _save_usage(self) -> None:
        blob = json.dumps(self.usage.to_json()).encode()
        for es in self.sets:
            es._fanout([lambda d=d: d.write_all(SYS_VOL, USAGE_PATH, blob)
                        for d in es.disks])

    # -- one cycle ------------------------------------------------------

    def scan_cycle(self) -> DataUsage:
        """One full pass over every set: format checks, walk, heal,
        usage rollup, persist."""
        from minio_tpu.utils import tracing
        with tracing.op_span("scanner", "scanner.cycle",
                             {"sets": len(self.sets)}):
            return self._scan_cycle_inner()

    def _scan_cycle_inner(self) -> DataUsage:
        check_drive_formats(self.sets, self.set_size)
        usage = DataUsage()
        state = {"deep_every": self.deep_every,
                 "counter": self.usage.cycles * 31}   # decorrelate samples
        buckets = {}
        for es in self.sets:
            for b in es.list_buckets():
                buckets.setdefault(b.name, BucketUsage())
        for bucket, bu in buckets.items():
            for es in self.sets:
                def hook(bkt, path, versions):
                    for cb in self.on_object:
                        cb(es, bkt, path, versions)
                scan_set_bucket(es, bucket, bu, state,
                                throttle=self.throttle, on_object=hook)
        usage.buckets = buckets
        for bu in buckets.values():
            usage.objects += bu.objects
            usage.versions += bu.versions
            usage.delete_markers += bu.delete_markers
            usage.total_size += bu.size
        usage.cycles = self.usage.cycles + 1
        usage.healed = self.usage.healed + state.get("healed", 0)
        usage.heal_failures = self.usage.heal_failures \
            + state.get("failures", 0)
        usage.last_update = time.time()
        self.usage = usage
        try:
            self._save_usage()
        except Exception:  # noqa: BLE001 - stats loss is not fatal
            pass
        return usage

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_cycle()
            except Exception:  # noqa: BLE001 - scanner must survive
                continue

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
