"""Server pools: federation of independent sets layers.

The analogue of the reference's erasureServerPools
(cmd/erasure-server-pool.go:52): each pool is an ErasureSets instance
(its own drives and set layout — the cluster expansion unit). New
objects land in the pool with the most free space unless a version of
the key already exists in some pool (cmd/erasure-server-pool.go:1084
PutObject / :1095 getPoolIdx); reads/deletes search pools in order;
listings merge across pools.
"""

from __future__ import annotations

from typing import Optional, Sequence

from minio_tpu.object.multipart import UploadNotFound
from minio_tpu.object.sets import merge_list_pages
from minio_tpu.object.types import (BucketNotFound, ListObjectsInfo,
                                    MethodNotAllowed, ObjectNotFound,
                                    VersionNotFound)

_MISSES = (ObjectNotFound, VersionNotFound)


class DecomUnavailable(Exception):
    """Every pool is draining: no placement target exists."""


class ServerPools:
    """Top-level ObjectLayer over one or more pools."""

    def __init__(self, pools: Sequence):
        if not pools:
            raise ValueError("at least one pool required")
        self.pools = list(pools)
        # Peer fan-out hook: callable(bucket) invoked after every
        # bucket-metadata mutation through this layer, so a distributed
        # boot can broadcast cache invalidations (grid.peers); firing
        # at the layer that owns the write keeps future callers from
        # silently bypassing the broadcast.
        self.on_bucket_meta_change = None
        # Pool indices being drained (object/decom.py): excluded from
        # new-object placement, searched LAST so reads prefer the
        # destination copy during a drain.
        self.decommissioning: set[int] = set()
        self._decom = None             # active Decommission driver
        # Peer fan-out hook fired on drain status transitions so other
        # nodes re-sync their exclusion sets (grid.peers).
        self.on_decom_change = None
        # Distributed wiring (server.py): dsync lockers electing the
        # single migration coordinator (empty = single-node, no
        # election), and the foreground admission-pressure probe that
        # migration walks yield to (decom.MigrationGovernor).
        self.lockers: list = []
        self.migration_pressure = None
        self._janitor = None           # (thread, stop_event) when running

    # -- placement -----------------------------------------------------

    def _pool_order(self) -> list[int]:
        """Search order: draining pools LAST, so during a decommission
        reads find the destination's (complete, possibly newer) version
        stack before the source's leftover."""
        if not self.decommissioning:
            return list(range(len(self.pools)))
        return [i for i in range(len(self.pools))
                if i not in self.decommissioning] + \
            sorted(self.decommissioning)

    def _pool_of_existing(self, bucket: str, object_: str) -> Optional[int]:
        """Pool already holding any version of the key, else None.
        (MethodNotAllowed means the latest is a delete marker — the key
        still lives in that pool.)"""
        if len(self.pools) == 1:
            return 0
        for i in self._pool_order():
            p = self.pools[i]
            try:
                p.get_object_info(bucket, object_)
                return i
            except MethodNotAllowed:
                return i
            except _MISSES + (BucketNotFound,):
                continue
            # Transient errors (quorum loss, drive faults) propagate:
            # treating them as "not here" would write a NEW copy of the
            # key into another pool and split-brain the namespace.
        return None

    def _pool_for_new(self) -> int:
        candidates = [i for i in range(len(self.pools))
                      if i not in self.decommissioning]
        if not candidates:
            raise DecomUnavailable("every pool is decommissioning")
        if len(candidates) == 1:
            return candidates[0]
        return max(candidates, key=lambda i: self.pools[i].free_space())

    def _put_pool(self, bucket: str, object_: str) -> int:
        idx = self._pool_of_existing(bucket, object_)
        if idx is None or idx in self.decommissioning:
            # Existing versions in a draining pool stay readable there;
            # NEW versions must land where the drain is copying TO.
            return self._pool_for_new()
        return idx

    # -- buckets -------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        # A bucket already present in one pool (e.g. after cluster
        # expansion) must still be created in the others; BucketExists
        # only when every pool reports it.
        from minio_tpu.object.types import BucketExists
        exists = 0
        for p in self.pools:
            try:
                p.make_bucket(bucket)
            except BucketExists:
                exists += 1
        if exists == len(self.pools):
            raise BucketExists(bucket)

    def get_bucket_info(self, bucket: str):
        last: Exception = BucketNotFound(bucket)
        for p in self.pools:
            try:
                return p.get_bucket_info(bucket)
            except BucketNotFound as e:
                last = e
        raise last

    def list_buckets(self):
        seen: dict[str, object] = {}
        for p in self.pools:
            for b in p.list_buckets():
                if b.name not in seen or b.created < seen[b.name].created:
                    seen[b.name] = b
        return [seen[k] for k in sorted(seen)]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        not_found = 0
        for p in self.pools:
            try:
                p.delete_bucket(bucket, force=force)
            except BucketNotFound:
                not_found += 1
        if not_found == len(self.pools):
            raise BucketNotFound(bucket)
        self._fire_meta_change(bucket)

    def _fire_meta_change(self, bucket: str) -> None:
        cb = self.on_bucket_meta_change
        if cb is not None:
            try:
                cb(bucket)
            except Exception:  # noqa: BLE001 - fan-out must not fail writes
                pass

    # -- bucket metadata ----------------------------------------------

    def get_bucket_meta(self, bucket: str) -> dict:
        for p in self.pools:
            meta = p.get_bucket_meta(bucket)
            if meta:
                return meta
        return {}

    def set_bucket_meta(self, bucket: str, meta: dict) -> None:
        for p in self.pools:
            p.set_bucket_meta(bucket, meta)
        self._fire_meta_change(bucket)

    def invalidate_bucket_meta(self, bucket: str = "") -> None:
        for p in self.pools:
            p.invalidate_bucket_meta(bucket)

    def close(self) -> None:
        for p in self.pools:
            p.close()

    def bucket_versioning(self, bucket: str) -> bool:
        return bool(self.get_bucket_meta(bucket).get("versioning"))

    def set_bucket_versioning(self, bucket: str, status) -> None:
        """status: True/"Enabled", "Suspended", or False (off).
        Suspension is a distinct state (null-versionId writes replace
        the null version; Enabled-era versions survive) — both keys
        are managed here so every caller keeps them consistent."""
        meta = self.get_bucket_meta(bucket)
        meta["versioning"] = status is True or status == "Enabled"
        meta["versioning-suspended"] = status == "Suspended"
        self.set_bucket_meta(bucket, meta)

    # -- objects -------------------------------------------------------

    def put_object(self, bucket, object_, data, opts=None):
        return self.pools[self._put_pool(bucket, object_)].put_object(
            bucket, object_, data, opts)

    def _search(self, fn_name: str, bucket, object_, *args, **kw):
        last: Exception = ObjectNotFound(bucket, object_)
        for i in self._pool_order():
            try:
                return getattr(self.pools[i], fn_name)(bucket, object_,
                                                       *args, **kw)
            except _MISSES as e:
                last = e
        raise last

    def get_object(self, bucket, object_, opts=None):
        return self._search("get_object", bucket, object_, opts)

    def get_object_stream(self, bucket, object_, opts=None):
        return self._search("get_object_stream", bucket, object_, opts)

    def get_object_info(self, bucket, object_, opts=None):
        return self._search("get_object_info", bucket, object_, opts)

    def update_object_tags(self, bucket, object_, version_id="", tags=None):
        return self._search("update_object_tags", bucket, object_,
                            version_id, tags)

    def update_version_metadata(self, bucket, object_, version_id, mutate,
                                allow_delete_marker=False):
        return self._search("update_version_metadata", bucket, object_,
                            version_id, mutate, allow_delete_marker)

    def list_versions_all(self, bucket, object_):
        return self._search("list_versions_all", bucket, object_)

    def delete_object(self, bucket, object_, opts=None):
        from minio_tpu.object.types import DeleteOptions
        opts = opts or DeleteOptions()
        if self.decommissioning:
            marker = (opts.versioned or opts.null_marker) \
                and not opts.version_id
            if marker:
                # Markers stack where a write would land: the pool that
                # owns the key, or a survivor when the owner is draining
                # (stamped into a draining pool the marker would land
                # outside the migration snapshot and silently vanish).
                return self.pools[self._put_pool(bucket, object_)] \
                    .delete_object(bucket, object_, opts)
            # Version destruction applies to EVERY pool holding a copy:
            # during a drain the same version can exist in both source
            # and destination, and deleting only one resurrects it.
            deleted = None
            last: Exception = ObjectNotFound(bucket, object_)
            for i in self._pool_order():
                try:
                    deleted = self.pools[i].delete_object(bucket, object_,
                                                          opts)
                except _MISSES as e:
                    last = e
            if deleted is None:
                raise last
            return deleted
        # Delete markers must land in the pool that holds the key
        # (reference DeleteObject pool lookup); a plain missing key
        # surfaces from the first pool's semantics.
        idx = self._pool_of_existing(bucket, object_)
        if idx is None:
            idx = 0
        return self.pools[idx].delete_object(bucket, object_, opts)

    # -- decommission --------------------------------------------------

    def start_decommission(self, pool_idx: int, checkpoint_every=None):
        """Begin draining pool `pool_idx` into the others (reference:
        cmd/erasure-server-pool-decom.go StartDecommission)."""
        from minio_tpu.object import decom
        if self._decom is not None and \
                self._decom.state.get("status") == "draining" and \
                not self._decom.wait(timeout=0):
            raise decom.DecomError("a decommission is already running")
        kw = {} if checkpoint_every is None else \
            {"checkpoint_every": checkpoint_every}
        self._decom = decom.Decommission(self, pool_idx, **kw)
        self._decom.start()
        return self._decom

    def sync_decommission_markers(self) -> None:
        """Re-read the persisted decommission document and update this
        node's placement-exclusion set — the receiving half of the
        peer control plane (a drain started on another node must stop
        THIS node from placing new objects in the draining pool). Does
        NOT start a drain worker; exactly one node runs the walk."""
        from minio_tpu.object import decom
        for sig, rec in decom.load_doc(self).get("records", {}).items():
            idx = decom.find_pool_by_signature(self, sig)
            if idx is not None and rec.get("status") in (
                    "draining", "failed", "complete"):
                self.decommissioning.add(idx)

    def resume_decommission(self):
        """Boot-time resume: pick an unfinished drain up, re-walking
        from the START when the previous run recorded failures (the
        migrate is idempotent, and a failed key would otherwise be
        checkpointed past forever). Pools are located by drive-endpoint
        SIGNATURE, never by stored index — after the operator removes
        the drained pool, indices shift and a stale index would poison
        a live pool. Returns the driver or None."""
        from minio_tpu.object import decom
        self.sync_decommission_markers()
        for sig, rec in decom.load_doc(self).get("records", {}).items():
            idx = decom.find_pool_by_signature(self, sig)
            if idx is None or rec.get("status") not in ("draining",
                                                        "failed"):
                continue
            if rec.get("status") == "failed" or rec.get("failed"):
                rec.update(bucket="", marker="", failed=0)
            rec["status"] = "draining"
            rec["pool"] = idx
            d = decom.Decommission(self, idx, state=rec)
            try:
                d.start()
            except decom.LeaseHeld:
                # Another node already drives this drain; our markers
                # are synced, which is all this node needs.
                return None
            self._decom = d
            return self._decom
        return None

    def decommission_status(self):
        """Drain progress — served from ANY node: a live local driver
        answers directly, everyone else reads the coordinator's
        persisted (rev-voted, cluster-readable) checkpoint."""
        from minio_tpu.object import decom
        d = self._decom
        if d is not None and not d.wait(timeout=0):
            return dict(d.state)
        state = decom.load_state(self)
        if state is None and d is not None:
            return dict(d.state)
        return dict(state) if state else None

    def cancel_decommission(self):
        """Pause the active drain (checkpointed; resumable)."""
        if self._decom is not None:
            self._decom.stop()

    # -- rebalance -----------------------------------------------------

    def _rebalance_lock(self):
        import threading
        lock = getattr(self, "_rebal_mu", None)
        if lock is None:
            lock = self._rebal_mu = threading.Lock()
        return lock

    def start_rebalance(self, checkpoint_every=None):
        """Begin draining overfilled pools toward the cluster average
        (reference: cmd/erasure-server-pool-rebalance.go
        rebalanceStart). Check-and-create under a lock: two concurrent
        admin starts must not race two drivers onto one state file."""
        from minio_tpu.object import rebalance
        kw = {} if checkpoint_every is None else \
            {"checkpoint_every": checkpoint_every}
        with self._rebalance_lock():
            rb = getattr(self, "_rebalance", None)
            if rb is not None and rb.state.get("status") in (
                    "planning", "rebalancing") and not rb.wait(timeout=0):
                raise rebalance.RebalanceError(
                    "a rebalance is already running")
            self._rebalance = rebalance.Rebalance(self, **kw)
            self._rebalance.start()
            return self._rebalance

    def resume_rebalance(self):
        """Boot-time resume of an interrupted rebalance (the migrate is
        idempotent, so re-walking from the checkpoint is safe). Returns
        the driver or None."""
        from minio_tpu.object import rebalance
        state = rebalance.load_state(self)
        if not state or state.get("status") not in ("planning",
                                                    "rebalancing"):
            return None
        # A topology change invalidates per-pool indices; only resume a
        # PLANNED state when the pool count still matches. A run killed
        # mid-planning has no per-pool records yet — restart planning.
        if state.get("status") == "planning" or \
                len(state.get("pools", {})) != len(self.pools):
            state = None
        with self._rebalance_lock():
            rb = rebalance.Rebalance(self, state=state)
            try:
                rb.start()
            except rebalance.LeaseHeld:
                # Another node already drives this rebalance.
                return None
            self._rebalance = rb
            return self._rebalance

    def _rebalance_state_copy(self, rb):
        import json as _json
        # Deep copy: the worker mutates nested per-pool dicts
        # concurrently, and a shallow copy could change size under
        # the admin handler's JSON serializer.
        for _ in range(3):
            try:
                return _json.loads(_json.dumps(rb.state))
            except RuntimeError:
                continue
        return {"status": rb.state.get("status", "rebalancing")}

    def rebalance_status(self):
        """Rebalance progress — served from ANY node (same shape as
        decommission_status: live driver first, else the persisted
        rev-voted checkpoint any node can read)."""
        from minio_tpu.object import rebalance
        rb = getattr(self, "_rebalance", None)
        if rb is not None and not rb.wait(timeout=0):
            return self._rebalance_state_copy(rb)
        state = rebalance.load_state(self)
        if state is None and rb is not None:
            return self._rebalance_state_copy(rb)
        return dict(state) if state else None

    def stop_rebalance(self):
        """Pause the active rebalance (checkpointed; resumable)."""
        rb = getattr(self, "_rebalance", None)
        if rb is not None:
            rb.stop()

    # -- elastic janitor ----------------------------------------------

    def elastic_janitor_tick(self) -> list[str]:
        """One orphan-recovery pass: if the persisted decom/rebalance
        checkpoint says a walk is mid-flight but no LOCAL driver is
        alive, try to win the coordinator lease and resume it. On the
        node that lost its coordinator this is how the fleet heals — a
        SIGKILLed coordinator's lease expires after MTPU_GRID_LOCK_TTL
        and the next tick on any surviving node picks the walk up from
        the checkpoint. Explicit operator stops set state["paused"]
        and are never auto-resumed. Returns the walks resumed here."""
        from minio_tpu.object import decom, rebalance
        resumed = []
        d = self._decom
        if d is None or d.wait(timeout=0):
            st = decom.load_state(self)
            if st and st.get("status") == "draining" \
                    and not st.get("paused") \
                    and self.resume_decommission() is not None:
                resumed.append("decom")
        rb = getattr(self, "_rebalance", None)
        if rb is None or rb.wait(timeout=0):
            st = rebalance.load_state(self)
            if st and st.get("status") == "rebalancing" \
                    and not st.get("paused") \
                    and self.resume_rebalance() is not None:
                resumed.append("rebalance")
        return resumed

    def start_elastic_janitor(self, interval: Optional[float] = None):
        """Run the janitor on EVERY node (distributed boots): ticks
        every MTPU_ELASTIC_JANITOR_S seconds (default 10); the lease
        keeps at most one node actually driving."""
        import threading
        from minio_tpu.utils.env import env_float
        if self._janitor is not None:
            return
        if interval is None:
            interval = env_float("MTPU_ELASTIC_JANITOR_S", 10.0)
        stop = threading.Event()

        def run():
            while not stop.wait(interval):
                try:
                    self.elastic_janitor_tick()
                except Exception:  # noqa: BLE001 - next tick retries
                    pass

        t = threading.Thread(target=run, daemon=True,
                             name="elastic-janitor")
        self._janitor = (t, stop)
        t.start()

    def stop_elastic_janitor(self) -> None:
        if self._janitor is not None:
            self._janitor[1].set()
            self._janitor = None

    # -- multipart -----------------------------------------------------

    def new_multipart_upload(self, bucket, object_, opts=None):
        return self.pools[self._put_pool(bucket, object_)] \
            .new_multipart_upload(bucket, object_, opts)

    def _upload_pool(self, bucket, object_, upload_id):
        from minio_tpu.object import multipart as mp
        for p in self.pools:
            try:
                mp._read_upload(p.set_for(object_) if hasattr(p, "set_for")
                                else p, bucket, object_, upload_id)
                return p
            except UploadNotFound:
                continue
        raise UploadNotFound(upload_id)

    def put_object_part(self, bucket, object_, upload_id, part_number, data,
                        actual_size=None, nonce=""):
        return self._upload_pool(bucket, object_, upload_id).put_object_part(
            bucket, object_, upload_id, part_number, data,
            actual_size=actual_size, nonce=nonce)

    def get_multipart_upload(self, bucket, object_, upload_id):
        return self._upload_pool(bucket, object_, upload_id) \
            .get_multipart_upload(bucket, object_, upload_id)

    def complete_multipart_upload(self, bucket, object_, upload_id, parts):
        return self._upload_pool(bucket, object_, upload_id) \
            .complete_multipart_upload(bucket, object_, upload_id, parts)

    def abort_multipart_upload(self, bucket, object_, upload_id):
        return self._upload_pool(bucket, object_, upload_id) \
            .abort_multipart_upload(bucket, object_, upload_id)

    def list_parts(self, bucket, object_, upload_id, part_marker=0,
                   max_parts=1000):
        return self._upload_pool(bucket, object_, upload_id).list_parts(
            bucket, object_, upload_id, part_marker, max_parts)

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for p in self.pools:
            out.extend(p.list_multipart_uploads(bucket, prefix))
        out.sort(key=lambda r: (r.get("object", ""), r.get("initiated", 0)))
        return out

    # -- listing -------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000,
                     include_versions: bool = False) -> ListObjectsInfo:
        pages = []
        found = False
        # Pool SEARCH order (draining pools last): during a migration
        # the same key/version may exist in both source and destination
        # for a moment, and merge_list_pages keeps the FIRST copy seen
        # — the destination's, matching what reads resolve.
        for i in self._pool_order():
            try:
                pages.append(self.pools[i].list_objects(
                    bucket, prefix=prefix, marker=marker, delimiter=delimiter,
                    max_keys=max_keys, include_versions=include_versions))
                found = True
            except BucketNotFound:
                continue
        if not found:
            raise BucketNotFound(bucket)
        return merge_list_pages(pages, max_keys,
                                versioned=include_versions)

    # -- healing -------------------------------------------------------

    def heal_object(self, bucket, object_, version_id="", deep=False):
        return self._search("heal_object", bucket, object_, version_id,
                            deep=deep)

    def heal_bucket(self, bucket):
        out = {"bucket": bucket, "missing": 0, "healed": 0}
        for p in self.pools:
            r = p.heal_bucket(bucket)
            out["missing"] += r.get("missing", 0)
            out["healed"] += r.get("healed", 0)
        return out

    def drain_mrf(self, timeout: float = 10.0) -> None:
        for p in self.pools:
            if hasattr(p, "drain_mrf"):
                p.drain_mrf(timeout)
