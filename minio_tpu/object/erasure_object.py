"""Erasure object store: one erasure set of n disks.

The analogue of the reference's erasureObjects (cmd/erasure-object.go):
object CRUD with quorum semantics over a set of StorageAPI drives.

Data path (PutObject, reference hot loop cmd/erasure-object.go:1249 +
cmd/erasure-encode.go:69): the whole object is batched into stripe
tensors and encoded in ONE device pass per object (full 1 MiB blocks in
one [B, k, L] batch, ragged tail in a second) instead of the
reference's block-at-a-time SIMD loop — the TPU-first reshape of the
same math. Shards are bitrot-framed (vectorized HighwayHash across all
shards x blocks), staged to tmp on every drive in parallel threads, and
committed with quorum-counted atomic rename (write quorum = k, +1 when
k == m, reference: cmd/erasure-object.go:1326-1330).

Read path (GetObject, reference: cmd/erasure-object.go:309 +
cmd/erasure-decode.go): quorum-pick the version from all drives'
journals, read the k preferred shards (data shards first), verify
bitrot per block, and only run the GF reconstruct when shards are
missing — batched across all blocks in one device call.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import threading
import time as _time_mod
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

from minio_tpu.erasure.codec import CodecError, Erasure, ceil_frac
from minio_tpu.io.bufpool import global_pool
from minio_tpu.io.engine import EngineSaturated, IOEngine
from minio_tpu.ops.batcher import batch_force_mode
from minio_tpu.utils import deadline as deadline_mod
from minio_tpu.utils import tracing
from minio_tpu.utils.deadline import DeadlineExceeded
from minio_tpu.object.types import (BucketExists, BucketInfo, BucketNotEmpty,
                                    BucketNotFound, DeleteOptions,
                                    DeletedObject, GetOptions, InvalidRange,
                                    MethodNotAllowed, ObjectInfo,
                                    ObjectNotFound, PutOptions,
                                    ReadQuorumError, VersionNotFound,
                                    WriteQuorumError)
from minio_tpu.storage import bitrot
from minio_tpu.storage.local import (SYS_VOL, StorageError, VolumeExists,
                                     VolumeNotEmpty, VolumeNotFound)
from minio_tpu.storage import meta as metafmt
from minio_tpu.storage.meta import (ErasureInfo, FileInfo, FileNotFoundErr,
                                    MetaError, ObjectPartInfo,
                                    VersionNotFoundErr, new_uuid, now_ns)
from minio_tpu.utils.streams import Payload

BLOCK_SIZE = 1 << 20          # reference blockSizeV2 (cmd/object-api-common.go:37)
SMALL_FILE_THRESHOLD = 128 << 10  # inline threshold (storage-class.go:278)
STAGING_PREFIX = "staging"
# O(block) streaming: objects larger than one window stream through the
# encoder in fixed 32-block (32 MiB) windows with double-buffered shard
# writers — the analogue of the reference's 1 MiB-block readahead
# pipeline (cmd/erasure-object.go:1415-1428), widened so each window is
# one batched device encode. Peak memory is O(window), never O(object).
STREAM_WINDOW_BLOCKS = 32
STREAM_THRESHOLD = STREAM_WINDOW_BLOCKS * BLOCK_SIZE
# Streamed GETs decode and yield this many plaintext bytes per step.
# 32 MiB = 32 erasure blocks: at EC:4 that is k*32 = 256 shard blocks
# per window, enough streams for device-batched bitrot verification
# (ops/hh_device.framed_digests_eligible).
GET_WINDOW_BYTES = 32 << 20

# PUTs below this many full erasure blocks encode on the host codec
# even when the set runs the TPU backend (see _encode_and_frame).
MIN_DEVICE_BLOCKS = 8

_RESERVED_BUCKETS = {SYS_VOL}


def new_staging() -> str:
    """A fresh staging dir path, pid-tagged (`staging/p<pid>-<uuid>`)
    so the boot janitor (storage/local.sweep_stale_tmp) can tell a LIVE
    sibling worker's in-flight PUT from a crash leftover and never
    sweep the former."""
    import os as _os
    return f"{STAGING_PREFIX}/p{_os.getpid()}-{new_uuid()}"


class _Md5Stream:
    """Streaming etag md5 for the windowed PUT loop: a native digest
    context updated GIL-free — and folded INTO the pooled frame call
    (mtpu_put_frame_md5) when the window takes that path — with
    hashlib as the fallback."""

    __slots__ = ("_lib", "_ctx", "_h", "_folded")

    def __init__(self):
        self._h = None
        self._ctx = None
        self._folded = False
        try:
            from minio_tpu import native
            lib = native.load()
            if lib is not None and hasattr(lib, "mtpu_digest_init"):
                import ctypes
                self._lib = lib
                self._ctx = (ctypes.c_uint8 * 128)()
                lib.mtpu_digest_init(0, self._ctx)
                return
        except Exception:  # noqa: BLE001 - loader failure -> hashlib
            pass
        self._lib = None
        self._h = hashlib.md5()

    @property
    def native_ctx(self):
        return self._ctx

    def mark_folded(self) -> None:
        self._folded = True

    def take_folded(self) -> bool:
        folded, self._folded = self._folded, False
        return folded

    def update(self, data) -> None:
        if self._ctx is not None:
            from minio_tpu import native
            self._lib.mtpu_digest_update(0, self._ctx, native._u8(data),
                                         len(data))
        else:
            self._h.update(data)

    def hexdigest(self) -> str:
        if self._ctx is not None:
            import ctypes
            out = (ctypes.c_uint8 * 16)()
            self._lib.mtpu_digest_final(0, self._ctx, out)
            return bytes(out).hex()
        return self._h.hexdigest()


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - jax always present in-tree
        return False


@functools.lru_cache(maxsize=64)
def _framer_for(k: int, m: int):
    """Fused device encode+bitrot framer for one EC config (the PUT hot
    loop on TPU: RS parity, HighwayHash framing, and the on-disk byte
    layout in one device pipeline — ops/hh_device.make_encode_framer)."""
    from minio_tpu.ops.hh_device import make_encode_framer
    return make_encode_framer(_parity_matrix(k, m))


def _host_rows(k: int, m: int, stacked: np.ndarray) -> list[list]:
    """Host-codec equivalent of the fused framer's rows: per-drive
    lists over erasure blocks of (digest, block) piece tuples. Used as
    the stripe batcher's fallback (and its calibration rival)."""
    from minio_tpu.erasure.codec import _HOST
    b, _, shard = stacked.shape
    n = k + m
    if m:
        flat = np.ascontiguousarray(stacked.transpose(1, 0, 2)) \
            .reshape(k, b * shard)
        parity = np.asarray(_HOST.apply_matrix(_parity_matrix(k, m),
                                               flat)) \
            .reshape(m, b, shard).transpose(1, 0, 2)
    else:
        parity = np.zeros((b, 0, shard), dtype=np.uint8)
    blocks = np.concatenate([stacked, parity], axis=1)   # [B, n, S]
    digs = bitrot.hash_blocks_many(
        bitrot.DEFAULT_ALGORITHM, blocks.reshape(b * n, shard)) \
        .reshape(b, n, 32)
    return [[(digs[bi, i], blocks[bi, i]) for bi in range(b)]
            for i in range(n)]


@functools.lru_cache(maxsize=64)
def _mesh_framer_for(k: int, m: int):
    """Mesh-sharded cross-request framer for one EC config: the batch
    dim ("stripes from many requests") is pjit-sharded over every
    available chip with donated inputs (ops/hh_device.make_mesh_framer);
    degrades to the single-chip fused framer on one device."""
    from minio_tpu.ops.hh_device import make_mesh_framer
    return make_mesh_framer(_parity_matrix(k, m))


@functools.lru_cache(maxsize=64)
def _batcher_for(k: int, m: int):
    """Cross-request stripe batcher for one EC config: coalesces
    concurrent PUT windows into one mesh-wide device step when the
    measured device round trip beats the host codec (ops/batcher.py).
    Staging rides the global buffer pool so the coalesced window is
    one pooled host buffer donated into HBM."""
    from minio_tpu.ops.batcher import StripeBatcher
    return StripeBatcher(_mesh_framer_for(k, m),
                         functools.partial(_host_rows, k, m),
                         min_device_blocks=MIN_DEVICE_BLOCKS,
                         pool=global_pool(), name=f"{k}+{m}")


@functools.lru_cache(maxsize=64)
def _transform_batcher_for(k: int, m: int):
    """The fused transform plane's frame-stage batcher: same mesh
    framer / host-row rivalry as the PUT batcher, but a SEPARATE
    route ("transform") with its own calibration entry and
    MTPU_BATCH_FORCE pin — the transform pipeline's stored windows
    (post-compress/encrypt) coalesce and route on their own
    measurement, since their arrival pattern and sizes differ from raw
    PUT windows."""
    from minio_tpu.ops.batcher import StripeBatcher
    return StripeBatcher(_mesh_framer_for(k, m),
                         functools.partial(_host_rows, k, m),
                         min_device_blocks=MIN_DEVICE_BLOCKS,
                         pool=global_pool(), name=f"tf:{k}+{m}",
                         route="transform")


# -- the decode mirror: GET verify + reconstruct batchers -------------------

def _get_batch_min_blocks() -> int:
    try:
        v = int(os.environ.get("MTPU_GET_BATCH_MIN_BLOCKS", "")
                or MIN_DEVICE_BLOCKS)
        return v if v > 0 else MIN_DEVICE_BLOCKS
    except ValueError:
        return MIN_DEVICE_BLOCKS


def _host_deframe(stacked: np.ndarray):
    """Host twin of the device de-framer (the get batcher's fallback
    and calibration rival): vectorized HighwayHash of every framed
    block in `stacked` [B, k, 32+S], verdicts [B, k] plus the data
    payload as zero-copy views — field-identical to
    hh_device.make_mesh_deframer's run() + the get split_fn."""
    b, k, f = stacked.shape
    s = f - 32
    digs = bitrot.hash_blocks_many(
        bitrot.DEFAULT_ALGORITHM, stacked[:, :, 32:].reshape(b * k, s))
    want = stacked[:, :, :32].reshape(b * k, 32)
    ok = (digs == want).all(axis=1).reshape(b, k)
    return ok, stacked[:, :, 32:]


def _get_split(ok, off, c, member):
    """Demux one coalesced GET verify dispatch: the member's verdict
    rows plus its payload as views of its OWN framed window (the
    device returns only the B*k verdicts — blocks never ride the
    device->host link back)."""
    return ok[off:off + c], member[:, :, 32:]


def _get_concat(a, b):
    return (np.concatenate([a[0], b[0]]),
            np.concatenate([a[1], b[1]]))


@functools.lru_cache(maxsize=64)
def _get_batcher_for(k: int, m: int):
    """Cross-request GET verify batcher for one EC config: stacked
    framed windows [B, k, 32+shard] from concurrent GETs coalesce into
    one device de-framer dispatch (ops/hh_device.make_mesh_deframer)
    when the decode-route calibration says the device wins; the
    vectorized host hash is the byte-identical fallback. k == 1 is the
    shard-file verifier heal rides (one member per drive blob)."""
    from minio_tpu.ops.batcher import StripeBatcher
    from minio_tpu.ops.hh_device import make_mesh_deframer
    return StripeBatcher(make_mesh_deframer(k), _host_deframe,
                         min_device_blocks=_get_batch_min_blocks(),
                         pool=global_pool(), name=f"get:{k}+{m}",
                         route="get", split_fn=_get_split,
                         concat_fn=_get_concat)


def _host_apply_rows(rows: np.ndarray, stacked: np.ndarray) -> np.ndarray:
    """Host GF application of `rows` [r, k] to a stripe batch
    [B, k, S] -> [B, r, S] (the reconstruct batcher's fallback): the
    transform is per byte column, so the batch flattens into one wide
    host-codec call."""
    from minio_tpu.erasure.codec import _HOST
    b, k, s = stacked.shape
    flat = np.ascontiguousarray(stacked.transpose(1, 0, 2)) \
        .reshape(k, b * s)
    out = np.asarray(_HOST.apply_matrix(rows, flat))
    return np.ascontiguousarray(
        out.reshape(rows.shape[0], b, s).transpose(1, 0, 2))


@functools.lru_cache(maxsize=256)
def _reconstruct_batcher_for(k: int, m: int, use: tuple,
                             missing_data: tuple):
    """Batched device reconstruct for one (EC config, surviving-shard
    set): degraded-read windows stack their survivors [B, k, S] and the
    decode-matrix rows for the missing data shards apply across the
    mesh in one dispatch (ops/rs_device.make_mesh_matrix). One batcher
    per survivor set — the common case is exactly one set per dead
    drive, so concurrent degraded GETs of that drive's objects coalesce
    cross-request just like healthy-path PUT/GET windows."""
    from minio_tpu.ops import gf256
    from minio_tpu.ops.batcher import StripeBatcher
    from minio_tpu.ops.rs_device import make_mesh_matrix
    dec = gf256.decode_matrix(k, m, use)
    rows = np.ascontiguousarray(dec[list(missing_data), :])
    return StripeBatcher(
        make_mesh_matrix(rows), functools.partial(_host_apply_rows, rows),
        min_device_blocks=_get_batch_min_blocks(),
        pool=global_pool(),
        name=f"rec:{k}+{m}:" + ",".join(map(str, use)),
        route="reconstruct",
        split_fn=lambda out, off, c, _member: out[off:off + c],
        concat_fn=lambda a, b: np.concatenate([a, b]))


def default_parity(set_size: int) -> int:
    """Default EC parity by set size (reference storage-class defaults:
    internal/config/storageclass/storage-class.go:355-367):
    1 drive -> 0, 2-3 -> 1, 4-5 -> 2, 6-7 -> 3, 8+ -> 4."""
    if set_size == 1:
        return 0
    if set_size <= 3:
        return 1
    if set_size <= 5:
        return 2
    if set_size <= 7:
        return 3
    return 4


def hash_order(key: str, cardinality: int) -> list[int]:
    """Deterministic shard distribution for a key: a rotation of
    [1..cardinality] starting at crc32(key) % cardinality (behavioural
    equivalent of the reference's hashOrder spread,
    cmd/erasure-metadata-utils.go:178)."""
    if cardinality <= 0:
        return []
    start = zlib.crc32(key.encode()) % cardinality
    return [1 + (start + i) % cardinality for i in range(cardinality)]


class ErasureSet:
    """One erasure set over n drives (LocalStorage or remote clients)."""

    def __init__(self, disks: Sequence, parity: Optional[int] = None,
                 backend=None, pool: Optional[ThreadPoolExecutor] = None):
        self.disks = list(disks)
        n = len(self.disks)
        if parity is not None and not 0 <= parity <= n // 2:
            # Parity above n/2 makes write quorum (k) smaller than read
            # quorum (n/2): acknowledged writes could be unreadable and
            # then purged as dangling. The reference rejects it in
            # storage-class config validation
            # (internal/config/storageclass/storage-class.go).
            raise ValueError(
                f"parity {parity} out of range for {n} drives "
                f"(need 0 <= parity <= {n // 2})")
        self.default_parity = default_parity(n) if parity is None else parity
        self.backend = backend
        self.pool = pool or ThreadPoolExecutor(max_workers=max(8, 2 * n))
        # Per-drive submission queues (io/engine.py): aligned fan-outs
        # ride these fixed crews instead of the shared pool, so one
        # drive's backlog convoys only itself and depth stays bounded.
        self.io = IOEngine([getattr(d, "endpoint", "") or str(i)
                            for i, d in enumerate(self.disks)])
        from minio_tpu.object.nslock import NSLockMap
        self.ns = NSLockMap()
        self._mrf = None
        self._mrf_lock = __import__("threading").Lock()
        # Warm-tier registry (object/tier.TierRegistry); None = no
        # tiering configured. Set at boot, shared across sets.
        self.tiers = None
        # Listing page cache with write invalidation (metacache).
        from minio_tpu.object.metacache import MetaCache
        self.metacache = MetaCache()
        # Quorum-fileinfo cache: repeat GET/HEAD of a key serves the
        # quorum-agreed (fi, fis) from memory instead of a k-drive
        # read_version fan-out. Invalidation rides the metacache bump
        # funnel (every namespace mutation already goes through it);
        # pre-forked workers additionally attach a shared-generation
        # observer (io/workers._wire_set).
        from minio_tpu.object.fi_cache import FileInfoCache
        self.fi_cache = FileInfoCache()
        self.metacache.listeners.append(self.fi_cache.invalidate_bucket)
        self._remote_set = any(
            _unwrap_disk(d).__class__.__module__
            == "minio_tpu.storage.remote"
            for d in self.disks if d is not None)
        if self._remote_set:
            # Distributed set: the cache stays ENABLED, gated on the
            # cross-node generation protocol (grid/coherence). The
            # distributed boot replaces this deny-all sentinel with
            # the live PeerCoherence.coherent gate; until then (and on
            # bare remote sets built without the protocol) lookups
            # answer misses — correct, just uncached — instead of
            # hits no invalidation contract covers.
            self.fi_cache.remote_gate = lambda: False
            self.metacache.remote_gate = lambda: False
        # Group-commit lanes (storage/group_commit): concurrent
        # small-object journal commits coalesce per drive into one
        # WAL-backed batch — the metadata twin of the stripe batcher.
        # Local sets only: every drive must implement the batched
        # commit protocol (remote drives and fault doubles that would
        # lose their injection seam fall back to the solo fan-out).
        from minio_tpu.storage import group_commit as gc_mod
        self.group_commit = None
        if gc_mod.enabled() and not self._remote_set and \
                all(_group_commit_capable(d) for d in self.disks):
            self.group_commit = gc_mod.GroupCommit(
                self.disks, self.io,
                name=f"set:{id(self) & 0xffff:x}")
            # One coalesced invalidation per batch per bucket, through
            # the same metacache-bump funnel per-request mutations use
            # (fi_cache listeners + worker shared-gen observers ride
            # along), fired BEFORE any member acks.
            self.group_commit.bump = self.metacache.bump
        # Read-kernel counters (admin info): windows served by the
        # fused native GET kernel, by the numpy path, and native
        # verifies that demoted to reconstruction. Incremented from
        # concurrent request/prefetch threads — dict += is a
        # read-modify-write, so a lock keeps the counts honest.
        self.get_kernel = {"native": 0, "numpy": 0, "demoted": 0,
                           "device": 0}
        self._gk_mu = threading.Lock()

    def close(self) -> None:
        """Release the set's background resources (fan-out executor,
        MRF worker). Repeated boot/stop cycles — sidecars, tests —
        would otherwise leak 8+ threads per lifecycle (caught by the
        leak harness, tests/test_leak_race.py). Under _mrf_lock with a
        closed sentinel: a racing lazy `mrf` access must not start a
        fresh worker after close() looked."""
        with self._mrf_lock:
            self._mrf_closed = True
            if self._mrf is not None:
                self._mrf.stop()
        if self.group_commit is not None:
            # Final WAL checkpoint rides along: graceful stops leave no
            # group-commit WALs for the next boot to replay.
            self.group_commit.close()
        self.pool.shutdown(wait=False)
        self.io.close()

    @property
    def mrf(self):
        """Lazy MRF heal queue (background worker starts on first use).
        After close(), enqueues go to a stopped queue (accepted but not
        worked — the set is going away) instead of starting a worker."""
        if self._mrf is None:
            with self._mrf_lock:
                if self._mrf is None:
                    from minio_tpu.object.healing import MRFQueue
                    q = MRFQueue(self)
                    if getattr(self, "_mrf_closed", False):
                        q.stop()
                    self._mrf = q
        return self._mrf

    # -- healing entry points ------------------------------------------

    def heal_object(self, bucket: str, object_: str, version_id: str = "",
                    deep: bool = False):
        from minio_tpu.object import healing
        return healing.heal_object(self, bucket, object_, version_id,
                                   deep=deep)

    def heal_bucket(self, bucket: str):
        from minio_tpu.object import healing
        return healing.heal_bucket(self, bucket)

    # -- multipart (object/multipart.py) -------------------------------

    def new_multipart_upload(self, bucket, object_, opts=None):
        from minio_tpu.object import multipart
        return multipart.new_multipart_upload(self, bucket, object_, opts)

    def put_object_part(self, bucket, object_, upload_id, part_number, data,
                        actual_size=None, nonce=""):
        from minio_tpu.object import multipart
        return multipart.put_object_part(self, bucket, object_, upload_id,
                                         part_number, data,
                                         actual_size=actual_size,
                                         nonce=nonce)

    def get_multipart_upload(self, bucket, object_, upload_id):
        from minio_tpu.object import multipart
        return multipart.get_multipart_upload(self, bucket, object_,
                                              upload_id)

    def complete_multipart_upload(self, bucket, object_, upload_id, parts):
        from minio_tpu.object import multipart
        return multipart.complete_multipart_upload(self, bucket, object_,
                                                   upload_id, parts)

    def abort_multipart_upload(self, bucket, object_, upload_id):
        from minio_tpu.object import multipart
        return multipart.abort_multipart_upload(self, bucket, object_,
                                                upload_id)

    def list_parts(self, bucket, object_, upload_id, part_marker=0,
                   max_parts=1000):
        from minio_tpu.object import multipart
        return multipart.list_parts(self, bucket, object_, upload_id,
                                    part_marker, max_parts)

    def list_multipart_uploads(self, bucket, prefix=""):
        from minio_tpu.object import multipart
        return multipart.list_multipart_uploads(self, bucket, prefix)

    # ------------------------------------------------------------------
    # fan-out helper
    # ------------------------------------------------------------------

    # Grace added to the request deadline when collecting fan-out
    # futures: the per-op deadline inside the worker (health wrapper,
    # grid call) is the precise one and should fire first; this bound
    # only catches workers on raw, unwrapped drives that can hang.
    _FANOUT_DEADLINE_SLOP = 0.25

    def _fanout(self, fns):
        """Run one callable per disk in parallel; returns (results, errors).

        A fns list aligned with self.disks (the common case: one op per
        drive) routes each entry through that drive's engine queue
        (io/engine.py) — bounded depth, fixed crew; anything else
        (subset cleanups, ad-hoc shapes) uses the shared pool. Jobs are
        fire-and-forget into shared result slots with ONE countdown
        latch for collection (one caller wait per fan-out, not one per
        drive — future-per-op handoff cost is real at 12+ drives). The
        caller's request deadline (utils/deadline.py) is re-bound
        inside each worker thread — thread locals do not cross the pool
        boundary on their own — and bounds the collection wait, so one
        hung drive can never hold the whole request past its budget."""
        dl = deadline_mod.current()
        n = len(fns)
        if dl is not None and dl.expired():
            # Budget already spent: answer without touching any drive.
            err = DeadlineExceeded("request deadline exceeded")
            return [None] * n, [err] * n

        results: list = [None] * n
        errors: list = [None] * n
        done: list = [False] * n
        pending = sum(1 for fn in fns if fn)
        if pending == 0:
            return results, [StorageError("disk offline")] * n
        all_done = threading.Event()
        latch_mu = threading.Lock()
        latch = [pending]

        def finish_one():
            with latch_mu:
                latch[0] -= 1
                if latch[0] == 0:
                    all_done.set()

        # Trace scope crosses the pool boundary the same way the
        # deadline does: captured here, re-bound in the worker. The
        # per-drive span wraps the whole queued op and carries the
        # queue-wait vs in-span (service) split — the child storage
        # span (health wrapper) then names the concrete disk op.
        tctx, tparent = tracing.capture() if tracing.ACTIVE else (None, 0)

        def make_job(i, fn):
            t_sub = _time_mod.perf_counter() if tctx is not None else 0.0

            def run():
                try:
                    with deadline_mod.bind(dl), \
                            tracing.bind(tctx, tparent):
                        if tctx is not None:
                            wait_ms = (_time_mod.perf_counter() - t_sub) \
                                * 1000.0
                            with tracing.span(
                                    "storage", "engine.op",
                                    {"drive": i,
                                     "queue_wait_ms": round(wait_ms, 3)}):
                                results[i] = fn()
                        else:
                            results[i] = fn()
                except BaseException as e:  # noqa: BLE001 - per-disk isolation
                    errors[i] = e
                finally:
                    done[i] = True
                    finish_one()
            return run

        per_drive = n == len(self.disks)
        for i, fn in enumerate(fns):
            if not fn:
                errors[i] = StorageError("disk offline")
                continue
            job = make_job(i, fn)
            if per_drive:
                try:
                    self.io.submit_nowait(i, job)
                except EngineSaturated as e:
                    # A saturated drive queue is a drive fault for THIS
                    # op: surfaced per disk, counted against quorum.
                    errors[i] = StorageError(str(e))
                    done[i] = True
                    finish_one()
            else:
                self.pool.submit(job)
        # One ABSOLUTE collection deadline for the whole fan-out: the
        # slop must not stack per hung worker, or n stuck drives
        # overshoot the budget n times over.
        if dl is None:
            all_done.wait()
        else:
            collect_by = dl.expires_at + self._FANOUT_DEADLINE_SLOP
            if not all_done.wait(timeout=max(
                    0.0, collect_by - _time_mod.monotonic())):
                # Workers stuck on something that ignores deadlines:
                # mark their slots and leave them to finish unobserved
                # (late completions write results nobody reads — the
                # snapshot below is what callers see).
                for i in range(n):
                    if fns[i] and not done[i]:
                        errors[i] = DeadlineExceeded(
                            "request deadline exceeded in drive fan-out")
        return list(results), list(errors)

    def _cleanup_fanout(self, fns):
        """Best-effort rollback/cleanup fan-out, SHIELDED from the
        request deadline (utils/deadline.shield): a request whose
        budget just expired still must not leave partially committed
        versions or staged shard files behind — skipping the rollback
        because the request timed out would create exactly the partial
        state the rollback exists to remove."""
        with deadline_mod.shield():
            return self._fanout(fns)

    # ------------------------------------------------------------------
    # buckets
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        if bucket in _RESERVED_BUCKETS:
            raise BucketExists(bucket)
        results, errors = self._fanout(
            [lambda d=d: d.make_vol(bucket) for d in self.disks])
        quorum = len(self.disks) // 2 + 1
        if sum(e is None for e in errors) < quorum:
            if any(isinstance(e, VolumeExists) for e in errors):
                raise BucketExists(bucket)
            raise WriteQuorumError(bucket)
        # Heal disks that failed transiently so the set stays consistent.
        self._cleanup_fanout([lambda d=d: _swallow(
            lambda: d.make_vol_if_missing(bucket))
            for d, e in zip(self.disks, errors) if e is not None])

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        results, errors = self._fanout(
            [lambda d=d: d.stat_vol(bucket) for d in self.disks])
        ok = [r for r in results if r is not None]
        if not ok:
            raise BucketNotFound(bucket)
        return BucketInfo(name=bucket, created=min(v.created for v in ok))

    def list_buckets(self) -> list[BucketInfo]:
        results, _ = self._fanout([lambda d=d: d.list_vols() for d in self.disks])
        seen: dict[str, int] = {}
        for vols in results:
            for v in vols or ():
                if v.name not in seen or v.created < seen[v.name]:
                    seen[v.name] = v.created
        return [BucketInfo(name=n, created=c)
                for n, c in sorted(seen.items())]

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        results, errors = self._fanout(
            [lambda d=d: d.delete_vol(bucket, force=force) for d in self.disks])
        if any(isinstance(e, VolumeNotEmpty) for e in errors):
            raise BucketNotEmpty(bucket)
        if all(isinstance(e, VolumeNotFound) for e in errors):
            raise BucketNotFound(bucket)
        ok = sum(e is None or isinstance(e, VolumeNotFound) for e in errors)
        if ok < len(self.disks) // 2 + 1:
            raise WriteQuorumError(bucket)
        # Drop bucket metadata so a recreated bucket starts fresh
        # (versioning state must not survive deletion).
        self.invalidate_bucket_meta(bucket)
        self.metacache.drop_bucket(bucket)
        self._cleanup_fanout([lambda d=d: _swallow(
            lambda: d.delete(SYS_VOL, f"buckets/{bucket}", recursive=True))
            for d in self.disks])

    # -- bucket metadata (versioning etc.; full subsystem arrives with
    #    IAM/policies — stored as quorum-replicated JSON under SYS_VOL,
    #    the shape of the reference's .minio.sys bucket metadata) --------

    def _bucket_meta_path(self, bucket: str) -> str:
        return f"buckets/{bucket}/bucket-meta.json"

    _BUCKET_META_TTL = 2.0

    def get_bucket_meta(self, bucket: str) -> dict:
        """Quorum-voted bucket metadata with an in-memory TTL cache
        (the reference caches bucket metadata cluster-wide; without a
        cache every object write pays an n-drive metadata fan-out).

        Local-only sets get a long TTL: in-process mutations call
        invalidate_bucket_meta directly and pre-forked siblings are
        covered by the meta generation file (io/workers._wire_set), so
        the TTL is not a coherence mechanism there — the short 2 s
        window is kept only for distributed sets, where a PEER node's
        bucket-meta write reaches us through best-effort invalidation
        and the TTL is the backstop."""
        import time as _time
        cache = getattr(self, "_bmeta_cache", None)
        if cache is None:
            cache = self._bmeta_cache = {}
        ttl = self._BUCKET_META_TTL if getattr(self, "_remote_set", True) \
            else 60.0
        hit = cache.get(bucket)
        if hit is not None and _time.monotonic() - hit[0] < ttl:
            return hit[1]
        meta = self._get_bucket_meta_uncached(bucket)
        cache[bucket] = (_time.monotonic(), meta)
        return meta

    def _get_bucket_meta_uncached(self, bucket: str) -> dict:
        import json
        results, _ = self._fanout(
            [lambda d=d: d.read_all(SYS_VOL, self._bucket_meta_path(bucket))
             for d in self.disks])
        votes: dict[bytes, int] = {}
        for r in results:
            if r is not None:
                votes[r] = votes.get(r, 0) + 1
        if not votes:
            return {}
        blob = max(votes, key=lambda b: votes[b])
        try:
            return json.loads(blob)
        except ValueError:
            return {}

    def set_bucket_meta(self, bucket: str, meta: dict) -> None:
        import json
        blob = json.dumps(meta, sort_keys=True).encode()
        _, errors = self._fanout(
            [lambda d=d: d.write_all(SYS_VOL, self._bucket_meta_path(bucket),
                                     blob) for d in self.disks])
        self.invalidate_bucket_meta(bucket)
        if sum(e is None for e in errors) < len(self.disks) // 2 + 1:
            raise WriteQuorumError(bucket)

    def invalidate_bucket_meta(self, bucket: str = "") -> None:
        """Drop the TTL cache for one bucket ("" = all): the peer
        control plane calls this when another node rewrites bucket
        metadata, so policy/versioning changes take effect here
        immediately instead of after the TTL."""
        for cache in (getattr(self, "_bmeta_cache", None),
                      getattr(self, "_bexists_cache", None)):
            if cache is None:
                continue
            if bucket:
                cache.pop(bucket, None)
            else:
                cache.clear()

    def bucket_versioning(self, bucket: str) -> bool:
        return bool(self.get_bucket_meta(bucket).get("versioning"))

    def set_bucket_versioning(self, bucket: str, status) -> None:
        """status: True/"Enabled", "Suspended", or False (off).
        Suspension is a distinct state (null-versionId writes replace
        the null version; Enabled-era versions survive) — both keys
        are managed here so every caller keeps them consistent."""
        meta = self.get_bucket_meta(bucket)
        meta["versioning"] = status is True or status == "Enabled"
        meta["versioning-suspended"] = status == "Suspended"
        self.set_bucket_meta(bucket, meta)

    def _check_bucket(self, bucket: str) -> None:
        """Bucket existence, positive-cached for the metadata TTL: the
        reference answers this from its in-memory bucket metadata system
        rather than statting every drive per request — a per-PUT
        n-drive stat fan-out costs more than the GF encode. Deletions
        invalidate via invalidate_bucket_meta (local and peer paths)."""
        import time as _time
        if bucket in _RESERVED_BUCKETS:
            raise BucketNotFound(bucket)
        cache = getattr(self, "_bexists_cache", None)
        if cache is None:
            cache = self._bexists_cache = {}
        deadline = cache.get(bucket)
        if deadline is not None and _time.monotonic() < deadline:
            return
        results, _ = self._fanout(
            [lambda d=d: d.stat_vol(bucket) for d in self.disks])
        if not any(r is not None for r in results):
            raise BucketNotFound(bucket)
        cache[bucket] = _time.monotonic() + self._BUCKET_META_TTL

    # ------------------------------------------------------------------
    # quorum metadata
    # ------------------------------------------------------------------

    def _read_version_all(self, bucket: str, object_: str, version_id: str,
                          read_data: bool = False):
        return self._fanout(
            [lambda d=d: d.read_version(bucket, object_, version_id,
                                        read_data=read_data)
             for d in self.disks])

    @staticmethod
    def _quorum_fileinfo(fis: list, quorum: int):
        """Pick the version agreed by >= quorum disks (reference:
        findFileInfoInQuorum keyed on mod-time + data layout)."""
        groups: dict[tuple, list[int]] = {}
        for i, fi in enumerate(fis):
            if fi is None:
                continue
            key = (fi.mod_time, fi.storage_version_id(), fi.data_dir,
                   fi.deleted, fi.size)
            groups.setdefault(key, []).append(i)
        best = None
        for key, idxs in groups.items():
            if len(idxs) >= quorum:
                if best is None or key[0] > best[0][0]:
                    best = (key, idxs)
        if best is None:
            return None, []
        return fis[best[1][0]], best[1]

    def _get_object_fileinfo(self, bucket: str, object_: str,
                             version_id: str = "", read_data: bool = False,
                             stat_only: bool = False):
        """(fi, per-disk fis, errors) with read-quorum enforcement.

        Repeat lookups of an unchanged key are memory hits in the
        fileinfo cache — zero drive calls; the token protocol makes
        the insert race-free against concurrent mutations (see
        object/fi_cache.py). Only fully-healthy reads (every drive
        answered, quorum found) are cached: a degraded read must keep
        re-reading so heal progress is observed and the MRF hook in
        callers keeps firing.

        `stat_only` is the HEAD path: lookups and inserts ride the
        cache's large stat class (quorum fi only — fis comes back
        None), so metadata storms at high key cardinality neither
        evict the GET fast path's data-class entries nor pay repeat
        fan-outs."""
        if stat_only:
            fi = self.fi_cache.get_stat(bucket, object_, version_id)
            if fi is not None:
                return fi, None, [None] * len(self.disks)
        else:
            cached = self.fi_cache.get(bucket, object_, version_id,
                                       need_data=read_data)
            if cached is not None:
                fi, fis = cached
                return fi, fis, [None] * len(self.disks)
        token = self.fi_cache.token(bucket)
        fis, errors = self._read_version_all(bucket, object_, version_id,
                                             read_data=read_data)
        not_found = sum(isinstance(e, FileNotFoundErr) for e in errors)
        version_gone = sum(isinstance(e, VersionNotFoundErr) for e in errors)
        n = len(self.disks)
        if not_found > n // 2:
            self._check_bucket(bucket)
            # Dangling-object GC (reference: cmd/erasure-object.go:484
            # deleteIfDangling): a MINORITY of drives still carries
            # metadata for a key the majority definitively lacks —
            # the leftover of a failed write. Reap it so it can neither
            # resurrect via heal nor haunt listings. Only when every
            # non-holding drive answered a clean not-found: a transient
            # IO error could mean the metadata majority is merely
            # unreachable. The reap itself runs ASYNC under the key's
            # write lock with a re-read (this read path may hold the
            # read lock, and an unlocked delete would race an in-flight
            # PUT commit fan-out into destroying fresh shards).
            holders = [i for i, fi in enumerate(fis) if fi is not None]
            definitive = not_found + len(holders) == n
            if holders and definitive and not version_id:
                threading.Thread(
                    target=self._reap_dangling, args=(bucket, object_),
                    daemon=True, name="dangling-gc").start()
            raise ObjectNotFound(bucket, object_)
        if version_gone > n // 2:
            raise VersionNotFound(bucket, object_)
        # Read quorum = data shards of the stored object (reference:
        # getReadQuorum == dataBlocks).
        any_fi = next((f for f in fis if f is not None), None)
        if any_fi is None:
            _raise_for_quorum(errors, ReadQuorumError(bucket, object_),
                              quorum=n // 2 + 1)
        quorum = max(any_fi.erasure.data_blocks, n // 2) if any_fi.erasure.data_blocks \
            else n // 2 + 1
        fi, idxs = self._quorum_fileinfo(fis, quorum)
        if fi is None:
            _raise_for_quorum(errors, ReadQuorumError(bucket, object_),
                              quorum=quorum)
        if all(e is None for e in errors):
            if stat_only:
                self.fi_cache.put_stat(bucket, object_, version_id, fi,
                                       token)
            else:
                self.fi_cache.put(bucket, object_, version_id, fi, fis,
                                  read_data, token)
        return fi, fis, errors

    def _reap_dangling(self, bucket: str, object_: str) -> None:
        """Destroy a dangling minority version stack — re-verified
        under the key's WRITE lock so a concurrent PUT commit (which
        also holds it) can never lose freshly-written shards to the
        reaper."""
        try:
            with self.ns.write(bucket, object_):
                fis, errors = self._read_version_all(bucket, object_, "")
                n = len(self.disks)
                not_found = sum(isinstance(e, FileNotFoundErr)
                                for e in errors)
                holders = [i for i, fi in enumerate(fis)
                           if fi is not None]
                if holders and not_found + len(holders) == n \
                        and not_found > n // 2:
                    self._fanout([
                        lambda d=self.disks[i]: _swallow(
                            lambda: d.delete(bucket, object_,
                                             recursive=True))
                        for i in holders])
        except Exception:  # noqa: BLE001 - GC is best-effort
            pass

    # ------------------------------------------------------------------
    # encode helpers (the TPU-batched data path)
    # ------------------------------------------------------------------

    def _erasure(self, k: int, m: int) -> Erasure:
        return Erasure(k, m, BLOCK_SIZE, backend=self.backend)

    def _encode_object(self, data: bytes, k: int, m: int) -> np.ndarray:
        """Encode a whole object -> shards uint8 [k+m, shard_file_len].

        All full blocks go through the backend in one batched call;
        the ragged tail block goes in a second. This is where PutObject's
        per-block loop becomes one device step.
        """
        e = self._erasure(k, m)
        n = k + m
        total = len(data)
        if total == 0:
            return np.zeros((n, 0), dtype=np.uint8)
        full = total // BLOCK_SIZE
        tail = total - full * BLOCK_SIZE
        shard_size = e.shard_size()
        pieces: list[np.ndarray] = []
        if full:
            buf = np.frombuffer(data, dtype=np.uint8, count=full * BLOCK_SIZE)
            if k * shard_size == BLOCK_SIZE:
                stacked = buf.reshape(full, k, shard_size)
            else:
                # Split pads each block to k*ceil(block/k) with zeros
                # (reference Split semantics) — e.g. k=3 on 1 MiB blocks.
                stacked = np.zeros((full, k * shard_size), dtype=np.uint8)
                stacked[:, :BLOCK_SIZE] = buf.reshape(full, BLOCK_SIZE)
                stacked = stacked.reshape(full, k, shard_size)
            parity = self._apply_batch(e, stacked)           # [full, m, L]
            blocks = np.concatenate([stacked, parity], axis=1)  # [full, n, L]
            pieces.append(blocks.transpose(1, 0, 2).reshape(n, -1))
        if tail:
            tail_shards = e.split(data[full * BLOCK_SIZE:])
            parity = np.asarray(e.backend.apply_matrix(
                _parity_matrix(k, m), tail_shards)) if m else \
                np.zeros((0, tail_shards.shape[1]), dtype=np.uint8)
            pieces.append(np.concatenate([tail_shards, parity], axis=0))
        return np.concatenate(pieces, axis=1) if len(pieces) > 1 else pieces[0]

    def _apply_batch(self, e: Erasure, stacked: np.ndarray) -> np.ndarray:
        """[B, k, L] -> [B, m, L] parity via the device backend when it
        supports batching, else per-block."""
        if e.parity_blocks == 0:
            return np.zeros((stacked.shape[0], 0, stacked.shape[2]), np.uint8)
        pm = _parity_matrix(e.data_blocks, e.parity_blocks)
        be = e.backend
        cutover = getattr(be, "HOST_CUTOVER_BYTES", 0)
        if hasattr(be, "apply_matrix_device") and stacked.nbytes >= cutover:
            import jax.numpy as jnp
            out = be.apply_matrix_device(pm, jnp.asarray(stacked))
            return np.asarray(out)
        return np.stack([be.apply_matrix(pm, stacked[b])
                         for b in range(stacked.shape[0])])

    def _frame_pooled(self, data: bytes, k: int, m: int, full: int,
                      shard_size: int, md5=None):
        """Fused HOST encode+frame into a pooled aligned buffer: GF
        parity + HighwayHash + `digest || block` interleave in ONE
        GIL-free native call (native/native.cc mtpu_put_frame), output
        leased from the buffer pool instead of fresh per-put arrays.
        Returns (chunks, lease) covering the FULL blocks — chunks[i] a
        single memoryview into the lease — or None when the native
        library, the shape, or the algorithm rules it out.

        md5: optional _Md5Stream — when it carries a native context the
        WHOLE window (ragged tail included) md5-extends inside the same
        native call (mtpu_put_frame_md5) and the stream is marked
        folded, so the streaming PUT hot loop never touches the GIL for
        its per-window etag update."""
        if bitrot.DEFAULT_ALGORITHM != bitrot.HIGHWAYHASH256S \
                or k * shard_size != BLOCK_SIZE:
            return None
        from minio_tpu import native
        lib = native.load()
        if lib is None:
            return None
        n = k + m
        hsize = bitrot.digest_size(bitrot.DEFAULT_ALGORITHM)
        frame = hsize + shard_size
        span = full * frame
        lease = global_pool().lease(n * span)
        import ctypes

        from minio_tpu.utils.highwayhash import MAGIC_KEY
        src = np.frombuffer(data, dtype=np.uint8, count=full * BLOCK_SIZE)
        pm = np.ascontiguousarray(_parity_matrix(k, m)) if m \
            else np.zeros((0, k), dtype=np.uint8)
        out = (ctypes.c_uint8 * (n * span)).from_buffer(lease.raw)
        md5_ctx = md5.native_ctx if md5 is not None else None
        try:
            with tracing.span("kernel", "mtpu_put_frame",
                              {"blocks": full, "k": k, "m": m}) \
                    if tracing.ACTIVE else tracing.NOOP:
                if md5_ctx is not None:
                    lib.mtpu_put_frame_md5(
                        md5_ctx, native._u8(MAGIC_KEY), native._u8(pm),
                        src.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint8)),
                        full, k, m, shard_size, len(data), out)
                    md5.mark_folded()
                else:
                    lib.mtpu_put_frame(
                        native._u8(MAGIC_KEY), native._u8(pm),
                        src.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint8)),
                        full, k, m, shard_size, out)
        except BaseException:
            lease.release()
            raise
        mv = lease.view(n * span)
        return [[mv[i * span:(i + 1) * span]] for i in range(n)], lease

    def _frame_windows(self, data: bytes, k: int, m: int,
                       route: str = "put", md5=None):
        """Encode + bitrot-frame the object: (chunks, lease) where
        chunks is per-drive lists of framed byte chunks (shard index
        order) ready to write as shard files, and lease is a bufpool
        Lease the chunks view into (None when they own their bytes).
        The caller must release the lease — exactly once — after the
        chunks have been consumed; retain() it per concurrent consumer.

        On TPU with an eligible shape the full 1 MiB blocks run through
        the fused device pipeline (RS parity + HighwayHash + on-disk
        framing in one pass, ops/hh_device); on the host they run
        through the fused native kernel into a pooled buffer. Fallback
        is the batched numpy path (byte-identical output everywhere).
        """
        e = self._erasure(k, m)
        n = k + m
        total = len(data)
        shard_size = e.shard_size()
        if total == 0:
            return [[b""] for _ in range(n)], None
        full = total // BLOCK_SIZE
        # Honor the set's injected backend seam: the fused framer runs
        # only when this set was explicitly configured with a device
        # backend (server --ec-backend tpu/auto), so host/mock backends
        # see every encode, same as the tail path below. Eligible full
        # blocks route through the cross-request stripe batcher: windows
        # from concurrent PUTs coalesce into ONE device step (the batch
        # dim = stripes from many requests) when the batcher's measured
        # calibration says the device link wins; otherwise — including
        # a lone PUT with nobody to batch with — the host codec runs
        # with zero added latency (ops/batcher.py).
        # MTPU_BATCH_FORCE=device overrides the platform check: the
        # reproducibility knob must reach the REAL batched device route
        # on any host (CI plumbing proofs, the put_scaling sweep on
        # virtual devices) — without it, a non-TPU backend silently
        # measured the host path no matter what the batcher was forced
        # to, which is exactly the invisible degradation the knob
        # exists to rule out.
        batcher_for = _batcher_for if route == "put" \
            else _transform_batcher_for
        use_device = (full >= 1 and m > 0
                      and (_on_tpu() or batch_force_mode(route) == "device")
                      and hasattr(self.backend, "apply_matrix_device")
                      and BLOCK_SIZE % k == 0 and shard_size % 1024 == 0
                      # Once the batcher's calibration resolves to
                      # host, skip its queue entirely: the pooled
                      # native path below IS the fast host path.
                      and batcher_for(k, m).wants_device())
        chunks: list[list] = [[] for _ in range(n)]
        lease = None
        if use_device:
            buf = np.frombuffer(data, dtype=np.uint8,
                                count=full * BLOCK_SIZE)
            stacked = buf.reshape(full, k, shard_size)
            rows = batcher_for(k, m).frame(stacked)
            # rows[i] = per-block (digest, block) piece tuples. The
            # `hash || block` on-disk frame is assembled by the writer
            # from the pieces (reference cmd/bitrot-streaming.go:44-75
            # likewise writes hash then block; no interleaved buffer
            # ever exists).
            for i in range(n):
                for pieces in rows[i][:full]:
                    chunks[i].extend(pieces)
        elif full:
            pooled = self._frame_pooled(data, k, m, full, shard_size,
                                        md5=md5)
            if pooled is not None:
                chunks, lease = pooled
            else:
                shards = self._encode_object(
                    data[:full * BLOCK_SIZE] if total % BLOCK_SIZE
                    else data, k, m)
                chunks = [[f] for f in
                          bitrot.frame_shards_batch(shards, shard_size)]
        tail = total - full * BLOCK_SIZE
        if tail:
            tail_shards = e.split(data[full * BLOCK_SIZE:])
            parity = np.asarray(e.backend.apply_matrix(
                _parity_matrix(k, m), tail_shards)) if m else \
                np.zeros((0, tail_shards.shape[1]), dtype=np.uint8)
            framed_tail = bitrot.frame_shards_batch(
                np.concatenate([tail_shards, parity], axis=0)
                if m else tail_shards, shard_size)
            for i in range(n):
                chunks[i].append(framed_tail[i])
        return chunks, lease

    def _encode_and_frame(self, data: bytes, k: int, m: int,
                          pad_blocks: int = 0) -> list[list]:
        """Compatibility wrapper over _frame_windows for callers that
        want self-owned bytes (decom/restore paths, tests): any pooled
        views are copied out and the lease returns immediately.

        pad_blocks: retained for call-site compatibility; batch-shape
        stability is the stripe batcher's job (it pads coalesced
        batches to fixed buckets, so compiled shapes stay bounded no
        matter how requests interleave).
        """
        del pad_blocks
        chunks, lease = self._frame_windows(data, k, m)
        if lease is None:
            return chunks
        try:
            return [[bytes(c) for c in row] for row in chunks]
        finally:
            lease.release()

    # ------------------------------------------------------------------
    # Fused single-pass transform plane (object/transform.TransformSpec)
    # ------------------------------------------------------------------

    def _transform_frame_windows(self, data, k: int, m: int, spec):
        """Execute a TransformSpec over `data` (the LOGICAL body) next
        to the framer: ONE GIL-free native call computes the etag md5 +
        declared checksums, deflates into the block scheme, seals into
        DARE packages, and frames the stored stream's full erasure
        blocks (native/native.cc mtpu_transform_frame) — the
        composition of the layered pipeline's separate walks. Returns
        (framed_chunks, lease, stored_len, etag_hex); spec is filled
        with digests/metadata and its pre-commit verify hook has run.

        Where the transform-route batcher calibrates to the device,
        the native call skips its frame stage and the stored windows
        ride the mesh framer through _frame_windows(route="transform").
        Ineligible shapes (no native library, non-HighwayHash bitrot,
        k not dividing the block) fall back to the staged Python
        pipeline — byte-identical stored stream, counted as
        path=legacy."""
        import ctypes

        from minio_tpu import native
        from minio_tpu.crypto import compress as comp_mod
        from minio_tpu.crypto import sse as sse_mod
        from minio_tpu.object import transform as transform_mod

        plen = len(data)
        spec.plain_size = plen
        # native.feature honors the MTPU_TRANSFORM_FUSED kill-switch:
        # direct object-layer callers (bench legacy legs, tests) must
        # take the staged pipeline under "off" exactly like the S3
        # handler path does.
        lib = native.feature("mtpu_transform_frame")
        e = self._erasure(k, m)
        n = k + m
        shard_size = e.shard_size()
        if lib is None \
                or bitrot.DEFAULT_ALGORITHM != bitrot.HIGHWAYHASH256S \
                or plen == 0:
            return self._transform_staged(data, k, m, spec)
        use_device = (m > 0
                      and (_on_tpu()
                           or batch_force_mode("transform") == "device")
                      and hasattr(self.backend, "apply_matrix_device")
                      and BLOCK_SIZE % k == 0 and shard_size % 1024 == 0
                      and _transform_batcher_for(k, m).wants_device())
        frame_native = not use_device and k * shard_size == BLOCK_SIZE
        PKG, TAG = 64 * 1024, 16
        npkg = (plen + PKG - 1) // PKG if spec.encrypt else 0
        ncomp = (plen + comp_mod.BLOCK - 1) // comp_mod.BLOCK \
            if spec.compress else 0
        stored_cap = plen + npkg * TAG + ncomp * 1104 + 64
        scratch_cap = plen + ncomp * 1104 + 64 \
            if (spec.compress and spec.encrypt) else 0
        max_full = stored_cap // BLOCK_SIZE + 1
        frames_cap = n * max_full * (32 + shard_size) if frame_native \
            else 0
        lease = global_pool().lease(stored_cap + scratch_cap + frames_cap)
        from minio_tpu.utils.highwayhash import MAGIC_KEY
        flags = 1
        for algo, bit in (("sha256", 2), ("sha1", 4), ("crc32", 8)):
            if algo in spec.algos:
                flags |= bit
        if spec.compress:
            flags |= 16
        if spec.encrypt:
            flags |= 32
        if frame_native:
            flags |= 64
        digests = (ctypes.c_uint8 * 72)()
        comp_ends = (ctypes.c_int64 * max(1, ncomp))()
        info = (ctypes.c_int64 * 8)()
        src = np.frombuffer(data, dtype=np.uint8, count=plen)
        pm = np.ascontiguousarray(_parity_matrix(k, m)) if m \
            else np.zeros((0, k), dtype=np.uint8)
        stored_arr = (ctypes.c_uint8 * stored_cap).from_buffer(lease.raw)
        scratch_arr = (ctypes.c_uint8 * max(1, scratch_cap)).from_buffer(
            lease.raw, stored_cap) if scratch_cap else None
        framed_arr = (ctypes.c_uint8 * frames_cap).from_buffer(
            lease.raw, stored_cap + scratch_cap) if frames_cap else None
        try:
            with tracing.span("kernel", "mtpu_transform_frame",
                              {"bytes": plen, "k": k, "m": m,
                               "flags": flags}) \
                    if tracing.ACTIVE else tracing.NOOP:
                ret = lib.mtpu_transform_frame(
                    src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    plen, flags, native._u8(spec.enc_key or b"\0" * 32),
                    native._u8(spec.enc_nonce or b"\0" * 12), digests,
                    stored_arr, stored_cap, scratch_arr or stored_arr,
                    scratch_cap, comp_ends, max(1, ncomp),
                    comp_mod.BLOCK, native._u8(MAGIC_KEY),
                    pm.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    k, m, shard_size, BLOCK_SIZE,
                    framed_arr or stored_arr, frames_cap, info)
            if ret == -2:
                # Built without zlib (-DMTPU_NO_ZLIB): the compress
                # stage cannot run natively — the staged pipeline's
                # Python zlib path owns this shape.
                lease.release()
                lease = None
                return self._transform_staged(data, k, m, spec)
            if ret < 0:
                raise CodecError(f"mtpu_transform_frame failed: {ret}")
            stored_len, full = int(info[0]), int(info[1])
            spec.stored_size = stored_len
            spec.comp_used = bool(info[2])
            spec.digests = {"md5": bytes(digests[0:16])}
            if "sha256" in spec.algos:
                spec.digests["sha256"] = bytes(digests[16:48])
            if "sha1" in spec.algos:
                spec.digests["sha1"] = bytes(digests[48:68])
            if "crc32" in spec.algos:
                spec.digests["crc32"] = bytes(digests[68:72])
            spec.etag = spec.digests["md5"].hex()
            if spec.comp_used:
                spec.comp_ends = list(comp_ends[: int(info[7])])
                spec.meta.update(comp_mod.index_meta(plen, spec.comp_ends))
                if spec.encrypt:
                    # The DARE stream's plaintext is the COMPRESSED
                    # stream: patch the sse size the handler stamped
                    # with the pre-compression value.
                    spec.meta[sse_mod.META_SIZE] = str(spec.comp_ends[-1])
            spec.run_verify()
            # Frame stage: views of the native output + the ragged
            # stored tail through the split path, or the whole stored
            # stream through the transform-route batcher.
            stored_mv = lease.view(stored_len)
            if frame_native:
                hsize = 32
                frame = hsize + shard_size
                span = full * frame
                base = stored_cap + scratch_cap
                mv = lease.view(base + n * span)
                chunks = [[mv[base + i * span: base + (i + 1) * span]]
                          for i in range(n)]
                tail = stored_len - full * BLOCK_SIZE
                if tail:
                    framed_tail = self._frame_tail(
                        e, bytes(stored_mv[full * BLOCK_SIZE:stored_len]),
                        k, m, shard_size)
                    for i in range(n):
                        chunks[i].append(framed_tail[i])
                if stored_len == 0:
                    chunks = [[b""] for _ in range(n)]
                transform_mod.note_put("fused", plen, list(info[3:7]))
                return chunks, lease, stored_len, spec.etag
            # Device (or non-dividing-k) frame route: the stored bytes
            # re-enter the shared windowed framer under the transform
            # route label.
            chunks, flease = self._frame_windows(
                bytes(stored_mv[:stored_len]) if stored_len else b"",
                k, m, route="transform")
            transform_mod.note_put("fused", plen, list(info[3:7]))
            lease.release()
            lease = None
            return chunks, flease, stored_len, spec.etag
        except BaseException:
            if lease is not None:
                lease.release()
            raise

    def _frame_tail(self, e, tail: bytes, k: int, m: int,
                    shard_size: int):
        """Frame the sub-block ragged tail exactly like _frame_windows'
        tail path (split + parity + bitrot frame)."""
        tail_shards = e.split(tail)
        parity = np.asarray(e.backend.apply_matrix(
            _parity_matrix(k, m), tail_shards)) if m else \
            np.zeros((0, tail_shards.shape[1]), dtype=np.uint8)
        return bitrot.frame_shards_batch(
            np.concatenate([tail_shards, parity], axis=0)
            if m else tail_shards, shard_size)

    def _transform_staged(self, data, k: int, m: int, spec):
        """Staged (layered) execution of a TransformSpec for shapes the
        single native call cannot take: same stored bytes, same
        metadata, counted as path=legacy in the transform plane's
        split counters."""
        import hashlib as _hl
        import zlib as _zl

        from minio_tpu.crypto import compress as comp_mod
        from minio_tpu.crypto import dare as dare_mod
        from minio_tpu.crypto import sse as sse_mod
        from minio_tpu.object import transform as transform_mod

        data = bytes(data)
        plen = len(data)
        spec.plain_size = plen
        spec.digests = {}
        if "sha256" in spec.algos:
            spec.digests["sha256"] = _hl.sha256(data).digest()
        if "sha1" in spec.algos:
            spec.digests["sha1"] = _hl.sha1(data).digest()
        if "crc32" in spec.algos:
            import struct as _st
            spec.digests["crc32"] = _st.pack(
                ">I", _zl.crc32(data) & 0xFFFFFFFF)
        body = data
        if spec.compress and plen:
            result = comp_mod.compress(data)
            if result is not None:
                body, meta = result
                spec.comp_used = True
                spec.meta.update(meta)
        if spec.encrypt:
            sealed = dare_mod.seal_bulk(spec.enc_key, spec.enc_nonce, 0,
                                        body)
            if sealed is None:
                from minio_tpu.utils.streams import Payload as _P
                enc = dare_mod.EncryptingPayload(
                    _P.wrap(body), spec.enc_key, spec.enc_nonce)
                parts = []
                while True:
                    c = enc.read(1 << 20)
                    if not c:
                        break
                    parts.append(c)
                sealed = b"".join(parts)
            stored = sealed
            if spec.comp_used:
                spec.meta[sse_mod.META_SIZE] = str(len(body))
        else:
            stored = body
        spec.stored_size = len(stored)
        spec.digests["md5"] = _hl.md5(
            data if (spec.comp_used or not spec.encrypt)
            else stored).digest()
        spec.etag = spec.digests["md5"].hex()
        spec.run_verify()
        chunks, lease = self._frame_windows(stored, k, m,
                                            route="transform")
        transform_mod.note_put("legacy", plen)
        return chunks, lease, len(stored), spec.etag

    # ------------------------------------------------------------------
    # PutObject
    # ------------------------------------------------------------------

    def put_object(self, bucket: str, object_: str, data,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        """data: bytes, or a utils.streams.Payload for O(window)-memory
        streaming of large bodies (reference: PutObject streams 1 MiB
        blocks, cmd/erasure-object.go:1415)."""
        opts = opts or PutOptions()
        payload = Payload.wrap(data)
        if payload.size > STREAM_THRESHOLD:
            if opts.transform is not None:
                # The fused spec is a buffered-plane contract; silently
                # ignoring it here would commit plaintext under
                # encrypted metadata.
                raise ValueError(
                    "TransformSpec requires a buffered-size body "
                    f"(<= {STREAM_THRESHOLD} bytes)")
            return self._put_object_streaming(bucket, object_, payload, opts)
        return self._put_object_buffered(bucket, object_,
                                         payload.read_all(), opts)

    def _put_object_buffered(self, bucket: str, object_: str, data: bytes,
                             opts: PutOptions) -> ObjectInfo:
        if self.group_commit is not None and len(data) <= BLOCK_SIZE:
            # Track the WHOLE buffered-put body, not just the commit:
            # the lanes' early-close rule compares pending members to
            # in-flight requests, so a request still encoding must
            # already count — its members are coming, and closing a
            # batch without them costs a whole extra commit round.
            # Bodies over one erasure block stay untracked: their
            # encode can run tens of ms, and a lane waiting on one as
            # an expected member would stall every small PUT behind
            # the window cap (they still join batches opportunistically
            # when small traffic is in flight).
            with self.group_commit.tracking():
                return self._put_object_buffered_inner(bucket, object_,
                                                       data, opts)
        return self._put_object_buffered_inner(bucket, object_, data, opts)

    def _put_object_buffered_inner(self, bucket: str, object_: str,
                                   data: bytes,
                                   opts: PutOptions) -> ObjectInfo:
        self._check_bucket(bucket)
        n = len(self.disks)
        m = self.default_parity
        if opts.storage_class == "REDUCED_REDUNDANCY" and n > 1:
            m = max(1, min(m, 2))
        k = n - m
        write_quorum = k + (1 if k == m else 0)

        distribution = hash_order(f"{bucket}/{object_}", n)
        # Encode outside the namespace lock (pure compute); only the
        # commit fan-out below serializes against other ops on this key.
        e = self._erasure(k, m)
        shard_size = e.shard_size()
        if opts.transform is not None:
            # Fused single-pass plane: digest + compress + DARE + frame
            # in one native call (spec verify hook runs pre-commit
            # inside); `size` below is the STORED length — exactly what
            # a pre-transformed payload's len() was on the layered
            # path. The spec's metadata (compression index, corrected
            # sse size) lands in internal metadata with the rest.
            framed, frames_lease, size, etag = \
                self._transform_frame_windows(data, k, m, opts.transform)
            opts.internal_metadata.update(opts.transform.meta)
            etag = opts.etag or etag
        else:
            framed, frames_lease = self._frame_windows(data, k, m)
            size = len(data)
            etag = opts.etag or hashlib.md5(data).hexdigest()
        version_id = opts.version_id or (new_uuid() if opts.versioned else "")
        mod_time = opts.mod_time or now_ns()
        shard_file_len = e.shard_file_size(size)
        inline = shard_file_len <= SMALL_FILE_THRESHOLD and not opts.versioned \
            or shard_file_len <= SMALL_FILE_THRESHOLD // 8
        if inline and frames_lease is not None:
            # Inline data commits straight into xl.meta (no staging +
            # rename gate), so the journal must never reference pooled
            # memory a recycled buffer could tear under a late writer:
            # copy out now and return the lease immediately.
            framed = [[bytes(c) for c in row] for row in framed]
            frames_lease.release()
            frames_lease = None

        data_dir = "" if inline else new_uuid()
        metadata = _clean_user_meta(opts.user_metadata)
        metadata["etag"] = etag
        if opts.content_type:
            metadata["content-type"] = opts.content_type
        if opts.tags:
            metadata["x-amz-tagging"] = opts.tags
        metadata.update(opts.internal_metadata)

        def make_fi(shard_idx: int) -> FileInfo:
            return FileInfo(
                volume=bucket, name=object_, version_id=version_id,
                deleted=False, data_dir=data_dir, mod_time=mod_time,
                size=size, metadata=metadata,
                parts=[ObjectPartInfo(number=1, size=size,
                                      actual_size=size, etag=etag)],
                erasure=ErasureInfo(
                    data_blocks=k, parity_blocks=m, block_size=BLOCK_SIZE,
                    index=shard_idx + 1, distribution=tuple(distribution)),
                inline_data=_join_chunks(framed[shard_idx]) if inline else None,
            )

        staging = new_staging()

        def write_one(disk_idx: int):
            d = self.disks[disk_idx]
            shard_idx = distribution[disk_idx] - 1
            fi = make_fi(shard_idx)
            if inline:
                d.write_metadata(bucket, object_, fi)
            else:
                d.create_file(SYS_VOL, f"{staging}/{data_dir}/part.1",
                              list(framed[shard_idx]))
                d.rename_data(SYS_VOL, staging, fi, bucket, object_)

        def stage_one(disk_idx: int):
            d = self.disks[disk_idx]
            shard_idx = distribution[disk_idx] - 1
            d.create_file(SYS_VOL, f"{staging}/{data_dir}/part.1",
                          list(framed[shard_idx]))

        gc = self.group_commit
        used_group = False
        try:
            with self.ns.write(bucket, object_):
                if gc is not None and gc.worth_batching():
                    # Coalesced commit: journal writes ride the
                    # per-drive group lanes — one WAL-backed batch per
                    # drive per window instead of one durable commit
                    # per drive per request. Non-inline shards stage
                    # first (the solo engine fan-out), then the
                    # rename_data commits coalesce the same way.
                    used_group = True
                    from minio_tpu.storage.group_commit import GroupOp
                    if inline:
                        errors = gc.commit_fanout(
                            [GroupOp.write_meta(
                                bucket, object_,
                                make_fi(distribution[i] - 1))
                             for i in range(n)])
                    else:
                        _, serrors = self._fanout(
                            _leased_fns([lambda i=i: stage_one(i)
                                         for i in range(n)],
                                        frames_lease))
                        gerrors = gc.commit_fanout(
                            [GroupOp.rename(
                                SYS_VOL, staging,
                                make_fi(distribution[i] - 1),
                                bucket, object_)
                             if serrors[i] is None else None
                             for i in range(n)])
                        errors = [se if se is not None else ge
                                  for se, ge in zip(serrors, gerrors)]
                else:
                    if gc is not None:
                        gc.note_solo()
                    _, errors = self._fanout(
                        _leased_fns([lambda i=i: write_one(i)
                                     for i in range(n)], frames_lease))
        finally:
            # The producer's reference, released even when the lock
            # times out; per-drive references (_leased_fns) are
            # returned by the workers themselves.
            if frames_lease is not None:
                frames_lease.release()
                frames_lease = None
        ok = sum(e is None for e in errors)
        if ok < write_quorum:
            # Best-effort cleanup: committed versions on the disks that
            # succeeded, and staged shard files everywhere (a failed
            # rename_data leaves its staging dir behind).
            self._cleanup_fanout([lambda d=d: _swallow(
                lambda: d.delete_version(bucket, object_, version_id))
                for d, err in zip(self.disks, errors) if err is None])
            if not inline:
                self._cleanup_fanout([lambda d=d: _swallow(
                    lambda: d.delete(SYS_VOL, staging, recursive=True))
                    for d in self.disks])
            _raise_for_quorum(errors, WriteQuorumError(
                bucket, object_, f"wrote {ok}/{n}, need {write_quorum}"),
                quorum=write_quorum)
        if ok < n:
            # Partial success: queue immediate background repair of the
            # drives that missed the write (reference MRF hook,
            # cmd/erasure-object.go:1556-1594).
            self.mrf.enqueue(bucket, object_, version_id)
        if not used_group:
            # Group commits already fired ONE coalesced bump per batch
            # (before any member ack); a second per-request bump here
            # would undo the coalescing the lane exists for.
            self.metacache.bump(bucket)
        return ObjectInfo(bucket=bucket, name=object_, mod_time=mod_time,
                          size=size, etag=etag,
                          content_type=opts.content_type,
                          version_id=version_id,
                          user_metadata=dict(opts.user_metadata),
                          actual_size=size)

    def restore_version(self, bucket: str, object_: str, src_fi,
                        data: Optional[bytes],
                        skip_if_newer_null: bool = False) -> None:
        """Write one version copied from ANOTHER erasure set into this
        set's geometry — the decommission/rebalance transfer primitive
        (reference: cmd/erasure-server-pool-decom.go decommissionObject
        re-putting through the destination pool).

        `src_fi`: the source FileInfo (version id, mod time, metadata
        map, parts, deleted flag) — preserved verbatim so the version
        is indistinguishable from the original (same etag, same SSE
        params, same part boundaries for part-aware decryption).
        `data`: the full STORED byte stream (None for delete markers);
        re-encoded here because the destination's (k, m) geometry can
        differ from the source's."""
        self._check_bucket(bucket)
        n = len(self.disks)

        def newer_null_exists() -> bool:
            """Under the key lock: is there already a null version at
            least as new as the one being restored? There is only ONE
            null slot per key — restoring an old null (data OR marker)
            over a newer concurrently-written one would lose an
            acknowledged write."""
            if not skip_if_newer_null or src_fi.version_id:
                return False
            try:
                return any(v.version_id == "" and
                           v.mod_time >= src_fi.mod_time
                           for v in self.list_versions_all(bucket, object_))
            except ObjectNotFound:
                return False

        if src_fi.deleted:
            fi = FileInfo(volume=bucket, name=object_,
                          version_id=src_fi.version_id, deleted=True,
                          mod_time=src_fi.mod_time)
            with self.ns.write(bucket, object_):
                if newer_null_exists():
                    return
                _, errors = self._fanout(
                    [lambda d=d: d.write_metadata(bucket, object_, fi)
                     for d in self.disks])
            if sum(e is None for e in errors) < n // 2 + 1:
                raise WriteQuorumError(bucket, object_)
            self.metacache.bump(bucket)
            return
        from minio_tpu.object.tier import META_TIER
        if (src_fi.metadata or {}).get(META_TIER):
            # Transitioned version: the DATA lives in its warm tier;
            # only the metadata pointer migrates (re-encoding would
            # duplicate the tier copy locally and shadow nothing).
            fi = FileInfo(
                volume=bucket, name=object_,
                version_id=src_fi.version_id, deleted=False,
                mod_time=src_fi.mod_time, size=src_fi.size,
                metadata=dict(src_fi.metadata),
                parts=[dataclasses.replace(p)
                       for p in (src_fi.parts or [])])
            with self.ns.write(bucket, object_):
                if newer_null_exists():
                    return
                _, errors = self._fanout(
                    [lambda d=d: d.write_metadata(bucket, object_, fi)
                     for d in self.disks])
            if sum(e is None for e in errors) < n // 2 + 1:
                raise WriteQuorumError(bucket, object_)
            self.metacache.bump(bucket)
            return
        m = self.default_parity
        k = n - m
        write_quorum = k + (1 if k == m else 0)
        distribution = hash_order(f"{bucket}/{object_}", n)
        parts = list(src_fi.parts or [])
        if not parts:
            parts = [ObjectPartInfo(number=1, size=len(data or b""),
                                    actual_size=len(data or b""))]
        data_dir = new_uuid()
        staging = new_staging()
        # Frame each part independently: the read path opens part files
        # one by one and sizes shards per part.
        framed_parts = []
        off = 0
        for p in parts:
            framed_parts.append(
                (p.number, self._encode_and_frame(data[off:off + p.size],
                                                  k, m)))
            off += p.size

        def write_one(disk_idx: int):
            d = self.disks[disk_idx]
            shard_idx = distribution[disk_idx] - 1
            for num, framed in framed_parts:
                d.create_file(SYS_VOL, f"{staging}/{data_dir}/part.{num}",
                              list(framed[shard_idx]))
            fi = FileInfo(
                volume=bucket, name=object_,
                version_id=src_fi.version_id, deleted=False,
                data_dir=data_dir, mod_time=src_fi.mod_time,
                size=src_fi.size, metadata=dict(src_fi.metadata),
                parts=[dataclasses.replace(p) for p in parts],
                erasure=ErasureInfo(
                    data_blocks=k, parity_blocks=m, block_size=BLOCK_SIZE,
                    index=shard_idx + 1,
                    distribution=tuple(distribution)))
            d.rename_data(SYS_VOL, staging, fi, bucket, object_)

        with self.ns.write(bucket, object_):
            if newer_null_exists():
                self._cleanup_fanout([lambda d=d: _swallow(
                    lambda: d.delete(SYS_VOL, staging, recursive=True))
                    for d in self.disks])
                return
            _, errors = self._fanout(
                [lambda i=i: write_one(i) for i in range(n)])
        ok = sum(e is None for e in errors)
        if ok < write_quorum:
            self._cleanup_fanout([lambda d=d: _swallow(
                lambda: d.delete(SYS_VOL, staging, recursive=True))
                for d in self.disks])
            raise WriteQuorumError(bucket, object_)
        if ok < n:
            self.mrf.enqueue(bucket, object_, src_fi.version_id)
        self.metacache.bump(bucket)

    # ------------------------------------------------------------------
    # Streaming PutObject (O(window) memory)
    # ------------------------------------------------------------------

    def _stream_framed_writes(self, payload: Payload, k: int, m: int,
                              distribution: Sequence[int],
                              path_for) -> tuple[str, list]:
        """Windowed encode+frame with parallel streamed shard writers.

        Reads `payload` in STREAM_WINDOW_BLOCKS windows, frames each
        (device or host), and feeds per-drive bounded queues consumed by
        one writer thread per drive (`path_for(i) -> (disk, vol, path)`,
        written via create_file's iterator form). Memory is bounded by
        the window size times the queue depth; a dead writer drains its
        queue so the producer never blocks on it. Returns (md5 etag,
        per-drive error list). The reference's shape: parallelWriter
        goroutines fed block-by-block (cmd/erasure-encode.go:69).
        """
        import queue as queue_mod

        n = len(self.disks)
        window_bytes = STREAM_WINDOW_BLOCKS * BLOCK_SIZE
        qs = [queue_mod.Queue(maxsize=2) for _ in range(n)]
        errors: list = [None] * n
        dead = [False] * n
        sentinel_seen = [False] * n
        _SENTINEL = object()

        dl = deadline_mod.current()
        tctx, tparent = tracing.capture() if tracing.ACTIVE else (None, 0)

        def got_sentinel(i: int, c) -> bool:
            """Sentinel handling shared by every consumer of qs[i]. The
            sentinel is STICKY (re-queued on receipt): when a health-
            wrapped create_file times out, its abandoned pool worker is
            still blocked in gen()'s get() while the writer's drain
            loop also consumes — one sentinel with two consumers would
            park the loser forever (leaking a pool worker per timed-out
            stream, or hanging the producer's join). Re-queueing wakes
            every consumer; the producer has stopped feeding this
            queue, so the re-put can never block."""
            if c is _SENTINEL:
                sentinel_seen[i] = True
                qs[i].put(c)
                return True
            return False

        def writer(i: int):
            # Release hook for the window row currently being consumed:
            # rows framed into pooled buffers carry a per-consumer
            # reference (bufpool.Lease.retain) that must return exactly
            # once — at the next queue pull (row fully written), in the
            # drain loop (row skipped), or when the writer dies
            # mid-row. TWO threads can reach the in-flight hook (this
            # writer thread's finally, and a deadline-abandoned
            # health-pool worker still driving gen()), so the handoff
            # swaps the callback out under a lock: whoever swaps it
            # runs it, nobody runs it twice.
            in_mu = threading.Lock()
            inflight: list = []

            def finish_inflight():
                with in_mu:
                    cbs, inflight[:] = list(inflight), []
                for cb in cbs:
                    if cb is not None:
                        cb()

            try:
                with deadline_mod.bind(dl), tracing.bind(tctx, tparent):
                    disk, vol, path = path_for(i)

                    def gen():
                        while True:
                            c = qs[i].get()
                            finish_inflight()
                            if got_sentinel(i, c):
                                return
                            row, cb = c
                            with in_mu:
                                inflight.append(cb)
                            yield from row
                    disk.create_file(vol, path, gen())
            except Exception as exc:  # noqa: BLE001 - collected for quorum
                errors[i] = exc
                dead[i] = True
                while not sentinel_seen[i]:
                    c = qs[i].get()
                    if not got_sentinel(i, c):
                        # Drain-owned rows never enter inflight: this
                        # thread is their only holder.
                        _, cb = c
                        if cb is not None:
                            cb()
            finally:
                finish_inflight()

        import threading
        threads = [threading.Thread(target=writer, args=(i,), daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        # Streaming etag: a native md5 context that the pooled frame
        # call extends INSIDE the same GIL-free native pass as the
        # encode+frame (mtpu_put_frame_md5); windows that take the
        # device or fallback route update it explicitly (still native,
        # still no GIL held over the buffer walk).
        md5 = _Md5Stream()
        write_quorum = k + (1 if k == m else 0)
        stream_error: Optional[Exception] = None
        try:
            while True:
                if dl is not None:
                    dl.check()
                window = payload.read_exact(window_bytes)
                if not window:
                    break
                window_lease = None
                try:
                    framed, window_lease = self._frame_windows(
                        window, k, m, md5=md5)
                    if not md5.take_folded():
                        md5.update(window)
                    if n - sum(dead) < write_quorum:
                        raise WriteQuorumError(
                            "", "",
                            f"{sum(dead)}/{n} writers failed mid-stream")
                    for i in range(n):
                        if dead[i]:
                            continue
                        cb = None
                        if window_lease is not None:
                            window_lease.retain()
                            cb = window_lease.release
                        qs[i].put((framed[distribution[i] - 1], cb))
                finally:
                    # The producer's own reference; per-writer refs are
                    # returned by each consumer.
                    if window_lease is not None:
                        window_lease.release()
        except Exception as exc:  # noqa: BLE001 - unwind writers first
            stream_error = exc
        finally:
            for i in range(n):
                qs[i].put(_SENTINEL)
            for t in threads:
                t.join()
        if stream_error is not None:
            raise stream_error
        return md5.hexdigest(), errors

    def _put_object_streaming(self, bucket: str, object_: str,
                              payload: Payload,
                              opts: PutOptions) -> ObjectInfo:
        """Large-object PUT: stream windows to staged shard files, then
        quorum-commit with atomic renames under the namespace lock —
        encode and IO run unlocked, only the commit serializes (the
        reference's tmp-write + renameData commit discipline)."""
        self._check_bucket(bucket)
        n = len(self.disks)
        m = self.default_parity
        if opts.storage_class == "REDUCED_REDUNDANCY" and n > 1:
            m = max(1, min(m, 2))
        k = n - m
        write_quorum = k + (1 if k == m else 0)
        size = payload.size
        distribution = hash_order(f"{bucket}/{object_}", n)
        version_id = opts.version_id or (new_uuid() if opts.versioned else "")
        data_dir = new_uuid()
        staging = new_staging()

        def path_for(i: int):
            return self.disks[i], SYS_VOL, f"{staging}/{data_dir}/part.1"

        def cleanup_staging(disks=None):
            self._cleanup_fanout([lambda d=d: _swallow(
                lambda: d.delete(SYS_VOL, staging, recursive=True))
                for d in (disks if disks is not None else self.disks)])

        try:
            etag, errors = self._stream_framed_writes(
                payload, k, m, distribution, path_for)
            etag = opts.etag or etag
        except Exception:
            cleanup_staging()
            raise
        ok = sum(err is None for err in errors)
        if ok < write_quorum:
            cleanup_staging()
            _raise_for_quorum(errors, WriteQuorumError(
                bucket, object_, f"staged {ok}/{n}, need {write_quorum}"),
                quorum=write_quorum)

        mod_time = opts.mod_time or now_ns()
        metadata = _clean_user_meta(opts.user_metadata)
        metadata["etag"] = etag
        if opts.content_type:
            metadata["content-type"] = opts.content_type
        if opts.tags:
            metadata["x-amz-tagging"] = opts.tags
        metadata.update(opts.internal_metadata)

        def make_fi(shard_idx: int) -> FileInfo:
            return FileInfo(
                volume=bucket, name=object_, version_id=version_id,
                deleted=False, data_dir=data_dir, mod_time=mod_time,
                size=size, metadata=metadata,
                parts=[ObjectPartInfo(number=1, size=size,
                                      actual_size=size, etag=etag)],
                erasure=ErasureInfo(
                    data_blocks=k, parity_blocks=m, block_size=BLOCK_SIZE,
                    index=shard_idx + 1, distribution=tuple(distribution)))

        def commit_one(i: int):
            if errors[i] is not None:
                raise errors[i]
            self.disks[i].rename_data(SYS_VOL, staging,
                                      make_fi(distribution[i] - 1),
                                      bucket, object_)

        with self.ns.write(bucket, object_):
            _, cerrors = self._fanout(
                [lambda i=i: commit_one(i) for i in range(n)])
        ok = sum(e2 is None for e2 in cerrors)
        if ok < write_quorum:
            self._cleanup_fanout([lambda d=d: _swallow(
                lambda: d.delete_version(bucket, object_, version_id))
                for d, err in zip(self.disks, cerrors) if err is None])
            cleanup_staging()
            _raise_for_quorum(cerrors, WriteQuorumError(
                bucket, object_,
                f"committed {ok}/{n}, need {write_quorum}"),
                quorum=write_quorum)
        laggards = [d for d, err in zip(self.disks, cerrors)
                    if err is not None]
        if laggards:
            cleanup_staging(laggards)
            self.mrf.enqueue(bucket, object_, version_id)
        self.metacache.bump(bucket)
        return ObjectInfo(bucket=bucket, name=object_, mod_time=mod_time,
                          size=size, etag=etag,
                          content_type=opts.content_type,
                          version_id=version_id,
                          user_metadata=dict(opts.user_metadata),
                          actual_size=size)

    # ------------------------------------------------------------------
    # GetObject
    # ------------------------------------------------------------------

    def get_object(self, bucket: str, object_: str,
                   opts: Optional[GetOptions] = None) -> tuple[ObjectInfo, bytes]:
        opts = opts or GetOptions()
        # Namespace read lock: shares with other readers, excludes
        # put/delete/heal on this key (reference: GetObjectNInfo's NSLock).
        with self.ns.read(bucket, object_):
            return self._get_object_locked(bucket, object_, opts)

    def _prepare_get(self, bucket: str, object_: str, opts: GetOptions):
        """Shared GET preamble: quorum fileinfo, delete-marker mapping,
        range resolution. Returns (info, fi, fis, offset, length)."""
        fi, fis, errors = self._get_object_fileinfo(
            bucket, object_, opts.version_id, read_data=True)
        if any(e is not None for e in errors):
            # Some drive is missing this version's metadata: schedule a
            # background heal even if the read itself succeeds from the
            # healthy k (reference: heal-on-missing-metadata in
            # getObjectFileInfo's MRF hook).
            self.mrf.enqueue(bucket, object_, fi.version_id)
        if fi.deleted:
            # Latest-is-delete-marker reads 404 (NoSuchKey); naming the
            # marker's version explicitly is 405 (MethodNotAllowed) —
            # AWS semantics, as in the reference's toAPIError mapping.
            if opts.version_id:
                raise MethodNotAllowed(bucket, object_)
            raise ObjectNotFound(bucket, object_)
        info = self._to_object_info(bucket, object_, fi)

        total = fi.size
        if opts.range_spec is not None:
            offset, length = _resolve_range(opts.range_spec, total,
                                            bucket, object_)
        else:
            offset = opts.offset
            length = total - offset if opts.length < 0 else opts.length
            if offset < 0 or length < 0 or offset + length > total:
                raise InvalidRange(bucket, object_)
        info.range_start, info.range_length = offset, length
        return info, fi, fis, offset, length

    def _get_object_locked(self, bucket: str, object_: str,
                           opts: GetOptions) -> tuple[ObjectInfo, bytes]:
        info, fi, fis, offset, length = self._prepare_get(bucket, object_,
                                                          opts)
        if fi.size == 0 or length == 0:
            return info, b""
        return info, self._read_payload(bucket, object_, fi, fis,
                                        offset, length)

    def get_object_stream(self, bucket: str, object_: str,
                          opts: Optional[GetOptions] = None):
        """Streaming GET: (ObjectInfo, iterator of plaintext chunks).

        Decodes GET_WINDOW_BYTES block windows at a time, so memory is
        O(window) regardless of range size. The namespace read lock is
        held until the iterator is exhausted or closed (the reference's
        GetObjectNInfo reader-with-unlock-on-close)."""
        opts = opts or GetOptions()
        cm = self.ns.read(bucket, object_)
        cm.__enter__()
        try:
            info, fi, fis, offset, length = self._prepare_get(
                bucket, object_, opts)
        except BaseException:
            cm.__exit__(None, None, None)
            raise

        def gen():
            try:
                # Primer yield: the caller advances past it immediately
                # (below), so the generator is always STARTED — close()
                # on a never-started generator would skip this finally
                # and leak the namespace lock.
                yield b""
                if fi.size and length:
                    yield from self._iter_payload(bucket, object_, fi, fis,
                                                  offset, length)
            finally:
                cm.__exit__(None, None, None)
        g = gen()
        next(g)
        return info, g

    def get_object_file(self, bucket: str, object_: str,
                        opts: Optional[GetOptions] = None,
                        info: Optional[ObjectInfo] = None):
        """Sendfile source probe for the serve plane (s3/eventloop
        connection plane): (info, fd, offset, length) when this
        object's STORED bytes equal its plaintext and live contiguously
        in one local file — today the FS-warm-tier copy of a
        transitioned version. Erasure-resident objects are never
        eligible: every shard file interleaves bitrot digests with the
        blocks (`digest || block` framing), so no raw-byte file exists
        for them. Whole-object, unencrypted, uncompressed reads only;
        None when ineligible. The caller owns the returned fd.

        Pass `info` (an ObjectInfo already resolved for this exact
        version, e.g. from an open get_object_stream whose read lock
        is still held) to skip the quorum fileinfo fan-out — the probe
        then needs only the tier file open+fstat."""
        from minio_tpu.object import tier as tier_mod
        opts = opts or GetOptions()
        if opts.range_spec is not None or opts.offset:
            return None
        if info is None:
            with self.ns.read(bucket, object_):
                info, _fi, _fis, _offset, _length = self._prepare_get(
                    bucket, object_, opts)
        imeta = info.internal_metadata or {}
        if imeta.get("x-internal-sse-alg") \
                or imeta.get("x-internal-comp"):
            return None
        length = info.size
        name = imeta.get(tier_mod.META_TIER)
        if not name or self.tiers is None or length == 0:
            return None
        try:
            backend = self.tiers.get(name)
        except Exception:  # noqa: BLE001 - tier config drift
            return None
        local_path = getattr(backend, "local_path", None)
        if local_path is None:
            return None
        path = local_path(imeta.get(tier_mod.META_TIER_KEY, ""))
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return None
        if os.fstat(fd).st_size != length:
            # Stored size must equal the plaintext length for a raw
            # file copy (no transform); anything else is not ours
            # to stream.
            os.close(fd)
            return None
        return info, fd, 0, length

    def _window_descs(self, fi: FileInfo, offset: int,
                      length: int) -> list[tuple]:
        """(part_number, part_size, rel, step) windows covering
        [offset, offset+length), snapped to erasure-block boundaries
        within each part so consecutive windows never re-read a block."""
        parts = fi.parts or [ObjectPartInfo(number=1, size=fi.size,
                                            actual_size=fi.size)]
        descs: list[tuple] = []
        cum = 0
        for p in parts:
            p_lo = max(offset, cum)
            p_hi = min(offset + length, cum + p.size)
            pos = p_lo
            while pos < p_hi:
                rel = pos - cum
                end_rel = min(p.size,
                              (rel // BLOCK_SIZE) * BLOCK_SIZE
                              + GET_WINDOW_BYTES)
                step = min(p_hi - pos, end_rel - rel)
                descs.append((p.number, p.size, rel, step))
                pos += step
            cum += p.size
            if cum >= offset + length:
                break
        return descs

    def _iter_payload(self, bucket: str, object_: str, fi: FileInfo,
                      fis: list, offset: int, length: int):
        """Yield [offset, offset+length) as block-aligned windows.

        Readahead: while window i is on the wire to the client, window
        i+1 is already fetching/verifying/decoding through the
        per-drive engine queues — bounded to ONE window in flight, so
        memory stays O(window). Chunks decoded by the native kernel
        are POOLED-buffer views (pool -> decode -> socket, the read
        mirror of the PUT path's leased buffers): each is valid until
        the consumer pulls the next chunk (or closes the generator),
        when its lease returns to the pool. The caller's request
        deadline is re-bound inside the prefetch thread, and the
        namespace read lock (held by get_object_stream around this
        iterator) outlives every prefetch it issues."""
        from minio_tpu.object import tier as tier_mod
        if (fi.metadata or {}).get(tier_mod.META_TIER):
            # Transitioned version: stream the warm-tier range in
            # GET_WINDOW_BYTES windows instead of one O(range) blob.
            pos, end = offset, offset + length
            while pos < end:
                step = min(GET_WINDOW_BYTES, end - pos)
                yield self._tier_read(fi, pos, step)
                pos += step
            return
        descs = self._window_descs(fi, offset, length)
        if not descs:
            return
        inline_cache: dict = {}
        if len(descs) == 1:
            # Sub-window response (inline objects, small ranges, any
            # GET that fits one window): read on the calling thread —
            # there is nothing to prefetch, so the pool submit/join
            # round-trip is pure overhead — and hand the pooled view
            # straight to the socket, where the serve path gathers it
            # with the response head into ONE sendmsg.
            num, psize, rel, step = descs[0]
            chunk, lease = self._read_part_window_pooled(
                bucket, object_, fi, fis, num, psize, rel, step,
                inline_cache=inline_cache)
            try:
                yield chunk
            finally:
                if lease is not None:
                    lease.release()
            return
        dl = deadline_mod.current()
        tctx, tparent = tracing.capture() if tracing.ACTIVE else (None, 0)

        def read_desc(desc):
            num, psize, rel, step = desc
            with deadline_mod.bind(dl), tracing.bind(tctx, tparent):
                return self._read_part_window_pooled(
                    bucket, object_, fi, fis, num, psize, rel, step,
                    inline_cache=inline_cache)

        fut = self.pool.submit(read_desc, descs[0])
        lease = None
        try:
            for i in range(len(descs)):
                chunk, lease = fut.result()
                # Prefetch the NEXT window before handing this one to
                # the consumer: its drive reads overlap the socket
                # sends (and the native decode releases the GIL).
                fut = self.pool.submit(read_desc, descs[i + 1]) \
                    if i + 1 < len(descs) else None
                yield chunk
                if lease is not None:
                    lease.release()
                    lease = None
        finally:
            if lease is not None:
                lease.release()
            if fut is not None:
                # A prefetch is still in flight (consumer closed early
                # or a window failed): collect it so its lease returns
                # — abandoning the future would park a pooled buffer
                # until GC (the pool's leak net would count it).
                try:
                    _, l2 = fut.result()
                    if l2 is not None:
                        l2.release()
                except BaseException:  # noqa: BLE001 - already unwinding
                    pass

    def _tier_read(self, fi: FileInfo, offset: int,
                   length: int) -> Optional[bytes]:
        """Transitioned version? Fetch the stored byte range from its
        warm tier (reference: getTransitionedObjectReader,
        cmd/bucket-lifecycle.go); None for local versions."""
        from minio_tpu.object import tier as tier_mod
        name = (fi.metadata or {}).get(tier_mod.META_TIER)
        if not name:
            return None
        if self.tiers is None:
            raise StorageError(
                f"version is tiered to {name!r} but no tier registry "
                "is configured")
        backend = self.tiers.get(name)
        return backend.get(fi.metadata[tier_mod.META_TIER_KEY],
                           offset, length)

    def _read_payload(self, bucket: str, object_: str, fi: FileInfo,
                      fis: list, offset: int, length: int) -> bytes:
        """Read [offset, offset+length) across the object's parts.

        Each part is an independent erasure encode stored as part.N shard
        files (reference: multipart parts keep their own erasure framing,
        cmd/erasure-object.go per-part loop at :368-387); single-put
        objects are the one-part special case."""
        tb = self._tier_read(fi, offset, length)
        if tb is not None:
            return tb
        parts = fi.parts or [ObjectPartInfo(number=1, size=fi.size,
                                            actual_size=fi.size)]
        out = bytearray()
        cum = 0
        inline_cache: dict = {}
        for p in parts:
            p_lo = max(offset, cum)
            p_hi = min(offset + length, cum + p.size)
            if p_hi > p_lo:
                out += self._read_part_window(
                    bucket, object_, fi, fis, p.number, p.size,
                    p_lo - cum, p_hi - p_lo, inline_cache=inline_cache)
            cum += p.size
            if cum >= offset + length:
                break
        return bytes(out)

    def _read_part_window(self, bucket: str, object_: str, fi: FileInfo,
                          fis: list, part_number: int, part_size: int,
                          offset: int, length: int,
                          inline_cache: Optional[dict] = None) -> bytes:
        """Self-owned-bytes wrapper over _read_part_window_pooled for
        callers that hold the result past the read (buffered GET,
        tiering upload)."""
        chunk, lease = self._read_part_window_pooled(
            bucket, object_, fi, fis, part_number, part_size, offset,
            length, inline_cache=inline_cache)
        if lease is None:
            return chunk
        try:
            return bytes(chunk)
        finally:
            lease.release()

    def _read_part_window_pooled(self, bucket: str, object_: str,
                                 fi: FileInfo, fis: list, part_number: int,
                                 part_size: int, offset: int, length: int,
                                 inline_cache: Optional[dict] = None):
        """Gather only the erasure blocks covering the window inside one
        part: verified shard-block slices (k preferred, hedge to all),
        batched reconstruct of missing shards, block-major reassembly.
        I/O, hashing and memory are O(range), not O(object) — the
        reference's ShardFileOffset range math (cmd/erasure-coding.go:135).

        Returns (chunk, lease). The fast path is the fused native GET
        kernel (native/native.cc mtpu_get_frame): ONE GIL-free ctypes
        call verifies every shard block's HighwayHash digest and
        interleaves the data block-major straight into a pooled buffer;
        chunk is then a memoryview into `lease` and the caller owns one
        reference. The numpy path (native lib absent, non-default
        algorithm, missing/corrupt shards needing reconstruction)
        returns (bytes, None) — byte-identical output either way.

        `inline_cache`: per-REQUEST dict sharing resolved inline blobs
        across this request's windows and shard fetches — an inline
        journal read with the empty not-loaded sentinel re-fetches each
        holder's xl.meta at most once per request, not once per shard
        fetch per window."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        n = k + m
        e = self._erasure(k, m)
        shard_size = e.shard_size()
        shard_file_len = e.shard_file_size(part_size)
        hsize = bitrot.digest_size(bitrot.DEFAULT_ALGORITHM)
        frame = hsize + shard_size
        part_file = f"part.{part_number}"

        start_b = offset // BLOCK_SIZE
        end_b = (offset + length - 1) // BLOCK_SIZE
        # Per-shard data/framed byte windows covering those blocks.
        data_lo = start_b * shard_size
        data_hi = min(shard_file_len, (end_b + 1) * shard_size)
        framed_lo = start_b * frame
        framed_hi = min(bitrot.shard_file_size(shard_file_len, shard_size),
                        (end_b + 1) * frame)
        win_len = data_hi - data_lo

        # Which disk holds which shard index for THIS version.
        holders: dict[int, int] = {}  # shard_idx -> disk idx
        for disk_idx, dfi in enumerate(fis):
            if dfi is None or dfi.deleted:
                continue
            if (dfi.mod_time, dfi.data_dir) != (fi.mod_time, fi.data_dir):
                continue
            holders[dfi.erasure.index - 1] = disk_idx

        def resolve_inline(disk_idx: int) -> bytes:
            """This holder's full inline shard blob, re-read from its
            journal at most once per request when fis carries the
            empty not-loaded sentinel."""
            blob = fis[disk_idx].inline_data
            if blob:
                return blob
            if inline_cache is not None and disk_idx in inline_cache:
                return inline_cache[disk_idx]
            blob = self.disks[disk_idx].read_version(
                bucket, object_, fi.version_id,
                read_data=True).inline_data or b""
            if inline_cache is not None:
                inline_cache[disk_idx] = blob
            return blob

        def fetch_raw(shard_idx: int):
            """Raw framed bytes of this shard's block window (no verify)."""
            disk_idx = holders.get(shard_idx)
            if disk_idx is None:
                return None
            d = self.disks[disk_idx]
            dfi = fis[disk_idx]
            try:
                if dfi.inline_data is not None:
                    return resolve_inline(disk_idx)[framed_lo:framed_hi]
                return d.read_file(
                    bucket, f"{object_}/{fi.data_dir}/{part_file}",
                    offset=framed_lo, length=framed_hi - framed_lo)
            except DeadlineExceeded:
                # The REQUEST ran out of budget, not the shard out of
                # luck — must reach the quorum triage, not become a
                # silent missing shard.
                raise
            except Exception:  # noqa: BLE001 - bad shard == missing shard
                return None

        # Bitrot verification batches across shards AND blocks — on the
        # device when this set runs the TPU backend and the window is
        # big enough to fill vector tiles, vectorized-host otherwise
        # (read-side counterpart of the fused PUT pipeline; the
        # reference hashes per block in ReadAt,
        # cmd/bitrot-streaming.go:161-200).
        use_device = _on_tpu() and hasattr(self.backend,
                                           "apply_matrix_device")

        def verify(blobs):
            return bitrot.read_framed_blocks_many(
                blobs, shard_size, win_len, device=use_device)

        def fetch_many(shard_idxs):
            """Fetch a set of shards through their holders' per-drive
            engine queues: the fns list is aligned with self.disks (so
            _fanout routes it per drive), results return in shard
            order. Shards with no holder stay None."""
            n_disks = len(self.disks)
            fns: list = [None] * n_disks
            pos: dict[int, int] = {}
            for s in shard_idxs:
                di = holders.get(s)
                if di is None:
                    continue
                pos[s] = di
                fns[di] = (lambda s=s: fetch_raw(s))
            results, errs = self._fanout(fns)
            return ([results[pos[s]] if s in pos else None
                     for s in shard_idxs],
                    [errs[pos[s]] if s in pos else None
                     for s in shard_idxs])

        # Read data shards first; hedge with parity shards for failures.
        shards: list[Optional[np.ndarray]] = [None] * n
        results, ferrs = fetch_many(range(k))
        skip = offset - start_b * BLOCK_SIZE

        # Fast path: all k data shards present and whole -> ONE
        # verify+interleave pass over the window. Per-host calibration
        # picks between the batched DEVICE route (cross-request
        # coalesced de-framer dispatch, ops/batcher get route) and the
        # fused native host kernel — byte-identical outputs. A nonzero
        # bad-mask either way means bitrot: demote those shards to
        # missing and take the reconstruct path below (which
        # re-verifies, rebuilds, and enqueues the MRF heal).
        dev_got = self._device_get_window(results, k, m, shard_size,
                                          win_len, start_b, end_b,
                                          part_size)
        got = None
        if dev_got is not None:
            view, lease, bad, route = dev_got
            if not bad:
                # A coalesced batch below min_device_blocks resolves to
                # the batcher's vectorized host fallback even under a
                # device calibration — count it as the numpy path, not
                # a device window.
                self._count_get("device" if route == "device"
                                else "numpy")
                return view[skip:skip + length], lease
            got = (view, lease, bad)
        else:
            got = self._native_get_window(results, k, shard_size,
                                          win_len, start_b, end_b,
                                          part_size)
        if got is not None:
            view, lease, bad = got
            if not bad:
                self._count_get("native")
                return view[skip:skip + length], lease
            self._count_get("demoted")
            for s in range(k):
                if bad >> s & 1:
                    results[s] = None

        self._count_get("numpy")
        for s, r in enumerate(verify(results)):
            shards[s] = r
        missing = [s for s in range(k) if shards[s] is None]
        if missing:
            extra, ferrs2 = fetch_many(range(k, n))
            for j, r in enumerate(verify(extra)):
                shards[k + j] = r
            available = sum(1 for s in shards if s is not None)
            if available < k:
                _raise_for_quorum(
                    ferrs + ferrs2,
                    ReadQuorumError(bucket, object_,
                                    f"{available}/{n} shards readable"),
                    quorum=k, ok=available)
            self._decode_missing(e, k, m, shards, shard_size)
            # Bytes were served from reconstruction: heal in background
            # (reference: MRF enqueue on degraded reads,
            # cmd/erasure-object.go:399-417).
            self.mrf.enqueue(bucket, object_, fi.version_id)

        # Blocks interleave across shards: reassemble block-major, trimming
        # each block's zero padding (k*shard_size may exceed BLOCK_SIZE).
        out = bytearray()
        for b in range(start_b, end_b + 1):
            lo = (b - start_b) * shard_size
            hi = min((b - start_b + 1) * shard_size, win_len)
            chunk = b"".join(shards[s][lo:hi].tobytes() for s in range(k))
            take = min(BLOCK_SIZE, part_size - b * BLOCK_SIZE)
            out += chunk[:take]
        # `out` holds object bytes [start_b*BLOCK_SIZE, ...); cut the range.
        return bytes(out[skip:skip + length]), None

    def _count_get(self, path: str) -> None:
        with self._gk_mu:
            self.get_kernel[path] += 1

    def _wants_device_route(self, route: str) -> bool:
        """Platform gate for a decode-route device dispatch: the set
        must run a device-capable backend, and either this host is a
        TPU host or MTPU_BATCH_FORCE pins the route (the
        reproducibility knob must reach the REAL batched device route
        on any host — see _frame_windows' identical PUT gate)."""
        return (hasattr(self.backend, "apply_matrix_device")
                and (_on_tpu() or batch_force_mode(route) == "device"))

    def _device_get_window(self, results, k: int, m: int,
                           shard_size: int, win_len: int, start_b: int,
                           end_b: int, part_size: int):
        """Batched device verify of k fetched shard windows — the
        device twin of _native_get_window, riding the cross-request
        get batcher. The window's FULL frames stack into one member
        [full, k, 32+shard_size]; concurrent GETs' members coalesce
        into one mesh de-framer dispatch that recomputes every digest
        on device. The ragged tail frame (a part's short last block)
        verifies on host. Verified payload interleaves block-major
        into a pooled lease from the member's own bytes (views — the
        payload never rides the device link back).

        None when the route does not apply (calibration resolved to
        host, non-default algorithm, missing/short shards, no full
        frames); otherwise (view, lease, 0, route) on success or
        (None, None, bad_mask, route) — route is the dispatch path the
        batcher actually took ("device", or "host"/"bypass" when a
        coalesced batch fell below the device threshold), so the
        caller's path metrics stay honest."""
        if bitrot.DEFAULT_ALGORITHM != bitrot.HIGHWAYHASH256S \
                or win_len <= 0 or not self._wants_device_route("get"):
            return None
        sb = _get_batcher_for(k, m)
        nb = end_b - start_b + 1
        slast = win_len - (nb - 1) * shard_size
        hsize = bitrot.digest_size(bitrot.DEFAULT_ALGORITHM)
        frame = hsize + shard_size
        expect = nb * hsize + win_len
        blobs = []
        for r in results:
            if r is None or len(r) != expect:
                return None
            blobs.append(np.frombuffer(
                r if isinstance(r, (bytes, bytearray)) else bytes(r),
                dtype=np.uint8))
        full = nb if slast == shard_size else nb - 1
        if full < 1 or not sb.worth_batching(full):
            # Solo sub-threshold windows (the hot 1 MiB repeat GET with
            # no concurrency) keep the fused native kernel — the
            # batcher only wins when there is a device-sized window or
            # company to coalesce with.
            return None
        stacked = np.empty((full, k, frame), dtype=np.uint8)
        for i, arr in enumerate(blobs):
            stacked[:, i, :] = arr[:full * frame].reshape(full, frame)
        try:
            ok, data = sb.frame(stacked)
        except DeadlineExceeded:
            raise
        except Exception:  # noqa: BLE001 - device trouble != corruption
            return None
        route = sb.last_route()
        bad = 0
        for i in range(k):
            if not ok[:, i].all():
                bad |= 1 << i
        if full < nb:
            off = full * frame
            for i, arr in enumerate(blobs):
                want = arr[off:off + hsize].tobytes()
                tail = arr[off + hsize:off + hsize + slast]
                if bitrot.hash_block(bitrot.DEFAULT_ALGORITHM,
                                     tail) != want:
                    bad |= 1 << i
        if bad:
            return None, None, bad, route
        take_last = min(BLOCK_SIZE, part_size - end_b * BLOCK_SIZE)
        out_len = (nb - 1) * BLOCK_SIZE + min(take_last, k * slast)
        lease = global_pool().lease(out_len)
        try:
            out = lease.ndarray((out_len,))
            pos = 0
            for b in range(full):
                take = min(BLOCK_SIZE, out_len - pos)
                out[pos:pos + take] = data[b].reshape(-1)[:take]
                pos += take
            if full < nb:
                off = full * frame + hsize
                take = out_len - pos
                tail = np.empty(k * slast, dtype=np.uint8)
                for i, arr in enumerate(blobs):
                    tail[i * slast:(i + 1) * slast] = \
                        arr[off:off + slast]
                out[pos:pos + take] = tail[:take]
                pos += take
        except BaseException:
            lease.release()
            raise
        return lease.view(out_len), lease, 0, route

    def _decode_missing(self, e, k: int, m: int, shards, shard_size: int):
        """Fill missing DATA shards from k survivors, routing the GF
        rebuild through the batched device reconstruct
        (ops/rs_device.make_mesh_matrix via the reconstruct batcher)
        when this host's decode calibration says the device wins; the
        host codec path (e.decode_data_blocks) is the byte-identical
        fallback and still owns every edge shape (short survivor sets,
        zero-length shards, ragged-only windows)."""
        missing_data = [i for i in range(k)
                        if shards[i] is None or shards[i].size == 0]
        if not missing_data:
            return
        if not (m > 0 and self._wants_device_route("reconstruct")):
            e.decode_data_blocks(shards)
            return
        present = [i for i, s in enumerate(shards)
                   if s is not None and s.size > 0]
        if len(present) < k:
            e.decode_data_blocks(shards)     # surfaces ReconstructError
            return
        use = tuple(present[:k])             # same pick as the codec
        shard_len = shards[use[0]].shape[0]
        if any(shards[i].shape[0] != shard_len for i in use):
            e.decode_data_blocks(shards)     # surfaces ShardSizeError
            return
        full = shard_len // shard_size
        sb = _reconstruct_batcher_for(k, m, use, tuple(missing_data))
        if full < 1 or not sb.worth_batching(full):
            e.decode_data_blocks(shards)
            return
        stacked = np.empty((full, k, shard_size), dtype=np.uint8)
        for j, i in enumerate(use):
            stacked[:, j, :] = \
                shards[i][:full * shard_size].reshape(full, shard_size)
        try:
            out = sb.frame(stacked)          # [full, r, shard_size]
        except DeadlineExceeded:
            raise
        except Exception:  # noqa: BLE001 - device trouble -> host codec
            e.decode_data_blocks(shards)
            return
        tail = shard_len - full * shard_size
        rebuilt = [np.empty(shard_len, dtype=np.uint8)
                   for _ in missing_data]
        for r_i in range(len(missing_data)):
            rebuilt[r_i][:full * shard_size] = out[:, r_i, :].reshape(-1)
        if tail:
            from minio_tpu.ops import gf256
            dec = gf256.decode_matrix(k, m, use)
            tail_in = np.stack([shards[i][full * shard_size:]
                                for i in use])
            tout = np.asarray(e.backend.apply_matrix(
                dec[list(missing_data), :], tail_in))
            for r_i in range(len(missing_data)):
                rebuilt[r_i][full * shard_size:] = tout[r_i]
        for r_i, i in enumerate(missing_data):
            shards[i] = rebuilt[r_i]

    def _verify_shard_blob(self, blob, shard_size: int, data_size: int):
        """Verified un-framed data of ONE framed shard blob, or None on
        bitrot/short read — bitrot.read_framed_blocks_many's per-blob
        contract, with the full frames routed through the batched
        device verify (k=1 members of the get batcher) when calibration
        says the device wins. Heal's deep verification — including the
        drive-replacement bulk heal — fans one call per drive through
        the engine crews, so concurrent shard files coalesce into
        shared de-framer dispatches."""
        hsize = bitrot.digest_size(bitrot.DEFAULT_ALGORITHM)
        frame = hsize + shard_size
        nb = (data_size + shard_size - 1) // shard_size if shard_size \
            else 0
        full = nb if data_size == nb * shard_size else nb - 1
        use_device = hasattr(self.backend, "apply_matrix_device")
        if bitrot.DEFAULT_ALGORITHM != bitrot.HIGHWAYHASH256S \
                or full < 1 or not self._wants_device_route("get") \
                or len(blob) != bitrot.shard_file_size(data_size,
                                                       shard_size):
            arr, = bitrot.read_framed_blocks_many(
                [blob], shard_size, data_size, device=use_device)
            return arr
        sb = _get_batcher_for(1, 0)
        if not sb.worth_batching(full):
            arr, = bitrot.read_framed_blocks_many(
                [blob], shard_size, data_size, device=use_device)
            return arr
        arr8 = np.frombuffer(blob, dtype=np.uint8)
        member = arr8[:full * frame].reshape(full, 1, frame)
        try:
            ok, data = sb.frame(member)
        except DeadlineExceeded:
            raise
        except Exception:  # noqa: BLE001 - device trouble -> host path
            arr, = bitrot.read_framed_blocks_many(
                [blob], shard_size, data_size, device=use_device)
            return arr
        if not ok.all():
            return None
        tail = data_size - full * shard_size
        if tail:
            off = full * frame
            want = arr8[off:off + hsize].tobytes()
            tdat = arr8[off + hsize:off + hsize + tail]
            if bitrot.hash_block(bitrot.DEFAULT_ALGORITHM, tdat) != want:
                return None
        out = np.empty(data_size, dtype=np.uint8)
        out[:full * shard_size] = data.reshape(full, shard_size) \
            .reshape(-1)
        if tail:
            off = full * frame + hsize
            out[full * shard_size:] = arr8[off:off + tail]
        return out

    def _native_get_window(self, results, k: int, shard_size: int,
                           win_len: int, start_b: int, end_b: int,
                           part_size: int):
        """Run the fused native GET kernel over k fetched shard windows.

        None when the fast path does not apply (native lib absent,
        non-default bitrot algorithm, a shard missing or short — those
        need the reconstruct path). Otherwise (view, lease, 0) with the
        window's plaintext in a pooled lease the caller now owns, or
        (None, None, bad_mask) when verification failed bit-mask shards
        (the lease is already returned)."""
        if bitrot.DEFAULT_ALGORITHM != bitrot.HIGHWAYHASH256S \
                or win_len <= 0:
            return None
        from minio_tpu import native
        lib = native.load()
        if lib is None:
            return None
        nb = end_b - start_b + 1
        slast = win_len - (nb - 1) * shard_size
        hsize = bitrot.digest_size(bitrot.DEFAULT_ALGORITHM)
        expect = nb * hsize + win_len
        blobs = []
        for r in results:
            if r is None or len(r) != expect:
                return None
            blobs.append(r if isinstance(r, bytes) else bytes(r))
        take_last = min(BLOCK_SIZE, part_size - end_b * BLOCK_SIZE)
        out_len = (nb - 1) * BLOCK_SIZE + min(take_last, k * slast)

        import ctypes

        from minio_tpu.utils.highwayhash import MAGIC_KEY
        u8p = ctypes.POINTER(ctypes.c_uint8)
        # c_char_p views the bytes objects' buffers without copying;
        # `keep` pins them for the duration of the call.
        keep = [ctypes.c_char_p(b) for b in blobs]
        ptrs = (u8p * k)(*[ctypes.cast(c, u8p) for c in keep])
        lease = global_pool().lease(out_len)
        out = (ctypes.c_uint8 * out_len).from_buffer(lease.raw)
        try:
            with tracing.span("kernel", "mtpu_get_frame",
                              {"blocks": nb, "k": k}) \
                    if tracing.ACTIVE else tracing.NOOP:
                bad = lib.mtpu_get_frame(
                    native._u8(MAGIC_KEY), ptrs, k, shard_size, nb, slast,
                    BLOCK_SIZE, take_last, out)
        except BaseException:
            lease.release()
            raise
        finally:
            del out     # drop the ctypes export so the mmap can recycle
        if bad:
            lease.release()
            return None, None, int(bad)
        return lease.view(out_len), lease, 0

    # ------------------------------------------------------------------
    # info / delete / list
    # ------------------------------------------------------------------

    def get_object_info(self, bucket: str, object_: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        opts = opts or GetOptions()
        fi, _, _ = self._get_object_fileinfo(bucket, object_,
                                             opts.version_id,
                                             stat_only=True)
        if fi.deleted:
            # Same AWS mapping as get_object: 404 for latest-is-marker,
            # 405 when the marker's version is named explicitly.
            if opts.version_id:
                raise MethodNotAllowed(bucket, object_)
            raise ObjectNotFound(bucket, object_)
        return self._to_object_info(bucket, object_, fi)

    @staticmethod
    def _to_object_info(bucket: str, object_: str, fi: FileInfo) -> ObjectInfo:
        meta = dict(fi.metadata)
        etag = meta.pop("etag", "")
        ctype = meta.pop("content-type", "")
        tags = meta.pop("x-amz-tagging", "")
        internal = {k: meta.pop(k) for k in list(meta)
                    if k.startswith("x-internal-")}
        size = fi.size
        # Content transforms (SSE, compression) store the logical size
        # internally; the API surface reports it, the storage size
        # stays in fi. Compression's size wins when BOTH transforms are
        # present (compress-then-encrypt): the sse size is then the
        # DARE stream's plaintext = the COMPRESSED length, not the
        # object's logical bytes.
        logical = internal.get("x-internal-comp-size") \
            or internal.get("x-internal-sse-size")
        if logical is not None:
            try:
                size = int(logical)
            except (TypeError, ValueError):
                pass
        return ObjectInfo(bucket=bucket, name=object_, mod_time=fi.mod_time,
                          size=size, etag=etag, content_type=ctype,
                          version_id=fi.version_id, is_latest=fi.is_latest,
                          delete_marker=fi.deleted, user_metadata=meta,
                          actual_size=size, user_tags=tags,
                          internal_metadata=internal,
                          parts=list(fi.parts or []))

    def update_version_metadata(self, bucket: str, object_: str,
                                version_id: str,
                                mutate,
                                allow_delete_marker: bool = False) -> ObjectInfo:
        """Apply `mutate(meta_dict)` to one version's metadata in
        place: each quorum-agreeing drive's own journal copy is
        rewritten, preserving its shard index and inline data
        (reference: PutObjectTags-style updateObjectMeta,
        cmd/erasure-object.go:1925).  Delete markers refuse the update
        unless allow_delete_marker is set — replication stamps its
        COMPLETED/FAILED status onto markers, while user-facing tag
        paths must keep rejecting them."""
        self._check_bucket(bucket)
        with self.ns.write(bucket, object_):
            fis, errors = self._read_version_all(bucket, object_, version_id,
                                                 read_data=True)
            n = len(self.disks)
            quorum = n // 2 + 1
            fi, idxs = self._quorum_fileinfo(fis, quorum)
            if fi is None:
                raise ObjectNotFound(bucket, object_)
            if fi.deleted and not allow_delete_marker:
                raise MethodNotAllowed(bucket, object_)
            # Only drives holding the quorum-agreeing copy are written
            # and counted: a success on a stale-version drive must not
            # let the update claim quorum (reference bounds writes to
            # onlineDisks of the read quorum).
            agree = set(idxs)

            def write_one(i: int):
                dfi = fis[i]
                meta = dict(dfi.metadata)
                mutate(meta)
                self.disks[i].write_metadata(
                    bucket, object_,
                    dataclasses.replace(dfi, metadata=meta))

            _, werrs = self._fanout(
                [(lambda i=i: write_one(i)) if i in agree else None
                 for i in range(n)])
            ok = sum(1 for i in agree if werrs[i] is None)
            if ok < quorum:
                raise WriteQuorumError(bucket, object_)
            if len(agree) < n:
                # Drives outside the agreeing set are stale/missing:
                # background heal brings them (and the update) over.
                self.mrf.enqueue(bucket, object_, fi.version_id)
        self.metacache.bump(bucket)
        meta = dict(fi.metadata)
        mutate(meta)
        return self._to_object_info(bucket, object_,
                                    dataclasses.replace(fi, metadata=meta))

    def update_object_tags(self, bucket: str, object_: str,
                           version_id: str = "",
                           tags: Optional[str] = None) -> ObjectInfo:
        """Set (tags=str) or remove (tags=None) a version's object tags
        in place (reference: PutObjectTags, cmd/erasure-object.go:1925)."""
        def mutate(meta):
            if tags is None:
                meta.pop("x-amz-tagging", None)
            else:
                meta["x-amz-tagging"] = tags
        return self.update_version_metadata(bucket, object_, version_id,
                                            mutate)

    def transition_version(self, bucket: str, object_: str,
                           version_id: str, tier_name: str) -> None:
        """Move one version's DATA to a warm tier, leaving its metadata
        local with a pointer (reference: transitionObject,
        cmd/bucket-lifecycle.go). The stored byte stream ships verbatim
        (SSE/compression transforms stay intact), so reads through
        _tier_read are byte-identical to local reads."""
        from minio_tpu.object import tier as tier_mod
        if self.tiers is None:
            raise StorageError("no tier registry configured")
        backend = self.tiers.get(tier_name)    # resolve before touching
        self._check_bucket(bucket)
        # Phase 1 — read + upload WITHOUT the key lock: shipping a
        # large object to a remote tier can take minutes, and holding
        # ns.write through it would LockTimeout every client operation
        # on the key. (Memory is O(object) for the upload buffer — a
        # v1 bound; the reference streams.)
        with self.ns.read(bucket, object_):
            fis, errors = self._read_version_all(bucket, object_,
                                                 version_id,
                                                 read_data=True)
            n = len(self.disks)
            quorum = n // 2 + 1
            fi, idxs = self._quorum_fileinfo(fis, quorum)
            if fi is None:
                raise ObjectNotFound(bucket, object_)
            if fi.deleted or fi.metadata.get(tier_mod.META_TIER):
                return                    # marker / already transitioned
            data = self._read_payload(bucket, object_, fi,
                                      fis, 0, fi.size)
        remote_key = tier_mod.tier_object_key(
            "", bucket, object_, fi.version_id).lstrip("/")
        backend.put(remote_key, data)
        # Phase 2 — commit the pointer under the lock, re-validating
        # that the version is still the one we uploaded (an overwrite
        # or delete during the upload orphans our tier copy: remove it
        # and bail; the next scanner cycle re-evaluates).
        with self.ns.write(bucket, object_):
            # read_data=False: only metadata decides the commit; the
            # data was uploaded in phase 1 and must not be re-read
            # under the exclusive lock.
            fis2, _ = self._read_version_all(bucket, object_, version_id,
                                             read_data=False)
            fi2, idxs2 = self._quorum_fileinfo(fis2, quorum)
            if fi2 is None or fi2.deleted or fi2.mod_time != fi.mod_time \
                    or fi2.metadata.get(tier_mod.META_TIER):
                # A concurrent transition may have committed a pointer
                # to the SAME deterministic remote key — removing it
                # would destroy the winner's blob. Reclaim only when a
                # READABLE version provably does not reference our
                # upload; fi2 None (transient quorum loss) proves
                # nothing, and an orphaned blob is the tolerable
                # failure mode.
                if fi2 is not None and fi2.metadata.get(
                        tier_mod.META_TIER_KEY) != remote_key:
                    backend.remove(remote_key)
                return
            new_meta = dict(fi2.metadata)
            new_meta[tier_mod.META_TIER] = tier_name
            new_meta[tier_mod.META_TIER_KEY] = remote_key
            new_meta[tier_mod.META_TIER_SIZE] = str(len(data))
            agree = set(idxs2)

            def rewrite_one(i: int):
                dfi = fis2[i]
                self.disks[i].write_metadata(
                    bucket, object_,
                    dataclasses.replace(dfi, metadata=dict(new_meta),
                                        inline_data=None))
                # The local shard files are now garbage: reclaim.
                if dfi.data_dir:
                    _swallow(lambda: self.disks[i].delete(
                        bucket, f"{object_}/{dfi.data_dir}",
                        recursive=True))

            _, werrs = self._fanout(
                [(lambda i=i: rewrite_one(i)) if i in agree else None
                 for i in range(n)])
            ok = sum(1 for i in agree if werrs[i] is None)
            if ok < quorum:
                # The tier copy exists but the pointer didn't commit:
                # remove the orphan and fail (next cycle retries).
                backend.remove(remote_key)
                raise WriteQuorumError(bucket, object_)
            if len(agree) < n:
                self.mrf.enqueue(bucket, object_, fi.version_id)
        # The version's data just moved off-drive and its local shard
        # dirs are gone: cached fileinfo (ours and sibling workers')
        # must re-resolve or reads would chase deleted shard files
        # instead of the tier pointer.
        self.metacache.bump(bucket)

    def _tier_pointer(self, bucket: str, object_: str,
                      version_id: str) -> Optional[tuple[str, str]]:
        """(tier name, remote key) when the version was transitioned,
        else None — read BEFORE deletion (the pointer dies with the
        metadata) but acted on only AFTER the delete succeeds."""
        if self.tiers is None:
            return None
        from minio_tpu.object import tier as tier_mod
        for d in self.disks:
            try:
                fi = d.read_version(bucket, object_, version_id)
            except Exception:  # noqa: BLE001 - try another drive
                continue
            name = (fi.metadata or {}).get(tier_mod.META_TIER)
            if name:
                return name, fi.metadata.get(tier_mod.META_TIER_KEY, "")
            return None
        return None

    def delete_object(self, bucket: str, object_: str,
                      opts: Optional[DeleteOptions] = None) -> DeletedObject:
        opts = opts or DeleteOptions()
        self._check_bucket(bucket)
        with self.ns.write(bucket, object_):
            ptr = None
            if (opts.version_id or not opts.versioned) \
                    and not opts.null_marker:
                # (null_marker stacks a marker — the latest version
                # SURVIVES, so its warm-tier blob must too.)
                # Version destruction (not marker stacking): note a
                # transitioned version's tier pointer now; the blob is
                # reclaimed only AFTER the delete commits (removing it
                # first would lose the data if the delete then fails
                # quorum). Lives HERE, not in _delete_object_locked —
                # decommission's internal deletes migrate the pointer
                # and must keep the blob.
                ptr = self._tier_pointer(bucket, object_, opts.version_id)
            result = self._delete_object_locked(bucket, object_, opts)
            if ptr is not None:
                name, remote_key = ptr
                try:
                    self.tiers.get(name).remove(remote_key)
                except Exception:  # noqa: BLE001 - orphan tolerated
                    pass
            return result

    def _delete_object_locked(self, bucket: str, object_: str,
                              opts: DeleteOptions) -> DeletedObject:
        n = len(self.disks)
        write_quorum = n // 2 + 1

        if (opts.versioned or opts.null_marker) and not opts.version_id:
            # Versioned delete without a version: write a delete marker.
            # Suspended buckets stamp the NULL versionId instead of a
            # fresh one — write_metadata's add_version then REPLACES
            # the previous null version, exactly AWS's suspended-state
            # semantics (any Enabled-era versions stay untouched).
            marker_vid = "" if opts.null_marker \
                else (opts.marker_version_id or new_uuid())
            fi = FileInfo(volume=bucket, name=object_, version_id=marker_vid,
                          deleted=True, mod_time=now_ns(),
                          metadata=dict(opts.marker_metadata or {}))
            gc = self.group_commit
            used_group = False
            if gc is not None:
                # Delete markers are journal-only commits — the same
                # shape as inline PUTs, so a concurrent delete storm
                # coalesces through the same per-drive lanes.
                with gc.tracking():
                    if gc.worth_batching():
                        used_group = True
                        from minio_tpu.storage.group_commit import GroupOp
                        errors = gc.commit_fanout(
                            [GroupOp.write_meta(bucket, object_, fi)
                             for _ in self.disks])
                    else:
                        gc.note_solo()
                        _, errors = self._fanout(
                            [lambda d=d: d.write_metadata(
                                bucket, object_, fi)
                             for d in self.disks])
            else:
                _, errors = self._fanout(
                    [lambda d=d: d.write_metadata(bucket, object_, fi)
                     for d in self.disks])
            if sum(e is None for e in errors) < write_quorum:
                raise WriteQuorumError(bucket, object_)
            if not used_group:
                self.metacache.bump(bucket)
            return DeletedObject(object_name=object_, delete_marker=True,
                                 delete_marker_version_id=marker_vid or "null")

        _, errors = self._fanout(
            [lambda d=d: d.delete_version(bucket, object_, opts.version_id)
             for d in self.disks])
        ok = sum(e is None for e in errors)
        missing = sum(isinstance(e, (FileNotFoundErr, VersionNotFoundErr))
                      for e in errors)
        if ok + missing < write_quorum:
            raise WriteQuorumError(bucket, object_)
        if ok + missing < n and ok > 0:
            # A drive missed the delete: repair so listings/reads cannot
            # resurrect the version from the stale copy.
            self.mrf.enqueue(bucket, object_, opts.version_id)
        self.metacache.bump(bucket)
        return DeletedObject(object_name=object_, version_id=opts.version_id)

    def _walk_resolved(self, bucket: str, prefix: str,
                       start: str = "", shallow: bool = False):
        """Sorted (path, entry) stream — the metacache's production
        side. Per-drive sorted SCAN walks (storage/local.walk_scan:
        batched native journal decode; plain walk_dir for drives
        without it) over a MAJORITY of drives (any write quorum
        intersects the walked set, so committed objects are never
        invisible even when some drives missed the write), k-way
        merged, each key resolved from its journal copies into a
        trimmed stream entry. The walked set rotates per walk
        (reference askDisks rotation) so a drive failing mid-walk only
        shadows objects for some walks. `shallow` walks one level and
        passes subtree markers through (delimiter pages)."""
        import heapq
        from itertools import groupby

        from minio_tpu.storage.meta_scan import PREFIX_MARK

        base_dir = ""
        if "/" in prefix:
            base_dir = prefix.rsplit("/", 1)[0]

        def disk_iter(d):
            try:
                ws = getattr(d, "walk_scan", None)
                if ws is not None:
                    yield from ws(bucket, base_dir=base_dir,
                                  forward_from=max(start, prefix),
                                  shallow=shallow)
                else:
                    # Remote / legacy drives: stream raw journals; the
                    # resolver summarizes per blob (shallow callers
                    # gate on every drive supporting walk_scan).
                    for path, blob in d.walk_dir(
                            bucket, base_dir=base_dir,
                            forward_from=max(start, prefix)):
                        yield path, None, blob
            except Exception:  # noqa: BLE001 - drive loss tolerated
                return

        n_disks = len(self.disks)
        rotor = getattr(self, "_walk_rotor", 0)
        self._walk_rotor = (rotor + 1) % n_disks
        rotated = [self.disks[(rotor + i) % n_disks]
                   for i in range(n_disks)]
        walk_disks = rotated[:n_disks // 2 + 1]
        iters = [disk_iter(d) for d in walk_disks if d is not None]
        merged = heapq.merge(*iters, key=lambda kv: kv[0])
        for path, grp in groupby(merged, key=lambda kv: kv[0]):
            items = [(v, b) for _, v, b in grp]
            if any(v is PREFIX_MARK for v, _ in items):
                # Shallow subtree marker: present on ANY walked drive
                # => the prefix exists (same union the merged deep walk
                # would produce).
                yield path, PREFIX_MARK
                continue
            entry = self._resolve_walked(bucket, path, items, len(iters))
            if entry is not None:
                yield path, entry

    def _resolve_walked(self, bucket, path, items, total_walked):
        """Resolve one walked key's per-drive (summary, blob) copies to
        a stream entry.

        When every walked drive has the key and the copies agree on
        the latest version, the journal is authoritative (no extra I/O
        — the hot path): a summary covering listing needs becomes a
        trimmed ("s", vlist) entry with no Python journal parse at
        all; otherwise ONE copy's blob is parsed into a full ("m",
        maps) entry. Disagreement (a drive missed a delete/overwrite,
        or the object never reached all walked drives) falls back to a
        full quorum metadata read, exactly how the reference's
        metacache resolver escalates — a lone stale copy must not
        resurrect deleted objects, and a quorum-thin write must still
        be listed."""
        from minio_tpu.storage.meta import XLMeta
        from minio_tpu.storage.meta_scan import (FLAG_DELETED,
                                                 summary_sufficient)
        parsed = []      # (latest-key, vlist|None, blob|None, xl|None)
        for vlist, blob in items:
            if vlist is not None:
                if not vlist:
                    continue             # empty journal: nothing listed
                lv = vlist[0]
                latest = (lv[1], lv[3], bool(lv[0] & FLAG_DELETED),
                          lv[4])
                parsed.append((latest, vlist, blob, None))
            else:
                try:
                    xl = XLMeta.load(blob)
                    v0 = xl.versions[0]
                except Exception:  # noqa: BLE001 - unreadable copy
                    continue
                latest = (v0["mt"], v0["vid"],
                          v0.get("kind") == metafmt.KIND_DELETE_MARKER,
                          v0.get("ddir", "") or "")
                parsed.append((latest, None, blob, xl))
        agree = (len(parsed) == total_walked
                 and len({p[0] for p in parsed}) == 1)
        if agree:
            for _, vlist, _, _ in parsed:
                if vlist is not None and summary_sufficient(vlist):
                    return ("s", vlist)
            for _, _, blob, xl in parsed:
                if xl is None and blob is not None:
                    try:
                        xl = XLMeta.load(blob)
                    except Exception:  # noqa: BLE001
                        continue
                if xl is not None:
                    return ("m", list(xl.versions))
        try:
            fi, _, _ = self._get_object_fileinfo(bucket, path)
        except Exception:  # noqa: BLE001 - dangling / below quorum
            return None
        # Walked copies disagreed — only the quorum fi is trustworthy.
        return ("m", [fi.to_version_map()])

    def _shallow_ok(self, delimiter: str) -> bool:
        """Delimiter pages ride a one-level shallow walk when the
        delimiter is the path separator (collapse boundaries ==
        directory boundaries) and every drive can shallow-walk
        (storage/local.walk_scan; remote drives stream deep walks)."""
        if delimiter != "/" or os.environ.get(
                "MTPU_LIST_SHALLOW", "on").lower() in ("0", "off",
                                                       "false"):
            return False
        return all(d is not None
                   and getattr(d, "walk_scan", None) is not None
                   for d in self.disks)

    def _entry_fileinfos(self, bucket: str, path: str,
                         entry) -> list[FileInfo]:
        """Stream entry -> per-version FileInfos, latest first.

        Trimmed ("s") entries rebuild exactly the fields listings
        consume (identity with the full-journal path is golden-tested
        with the scanner on and off; `parts` is deliberately absent —
        no listing surface reads it)."""
        from minio_tpu.storage.meta_scan import (FLAG_DELETED,
                                                 FLAG_INLINE)
        kind, payload = entry
        if kind == "m":
            xl = metafmt.XLMeta()
            xl.versions = list(payload)
            try:
                return xl.list_versions(bucket, path)
            except Exception:  # noqa: BLE001 - empty maps
                return []
        out = []
        for i, (flags, mt, size, vid, ddir, etag, ctype, tags) in \
                enumerate(payload):
            fi = FileInfo(
                volume=bucket, name=path,
                version_id="" if vid == metafmt.NULL_VERSION_ID else vid,
                is_latest=(i == 0),
                deleted=bool(flags & FLAG_DELETED), mod_time=mt)
            meta = {}
            if etag:
                meta["etag"] = etag
            if ctype:
                meta["content-type"] = ctype
            if tags:
                meta["x-amz-tagging"] = tags
            fi.metadata = meta
            if not fi.deleted:
                fi.data_dir = ddir
                fi.size = size
                if flags & FLAG_INLINE:
                    fi.inline_data = b""     # marker: inline, not loaded
            out.append(fi)
        return out

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000,
                     include_versions: bool = False):
        """Sorted listing with prefix/marker/delimiter semantics, served
        from the shared metacache walk stream (reference:
        cmd/metacache-set.go:700): every page, every concurrent listing
        of the same prefix, and every follow-up within the reuse window
        consumes ONE background walk — a large bucket walks once, not
        once per page. Writes bump the bucket generation, orphaning the
        stream (object/metacache.py). "/"-delimiter pages use a SHALLOW
        stream (one directory level + subtree markers) so a browse page
        costs O(page) instead of O(subtree)."""
        import bisect

        from minio_tpu.object.types import ListObjectsInfo
        from minio_tpu.storage.meta_scan import PREFIX_MARK

        self._check_bucket(bucket)
        max_keys = max(1, min(max_keys, 1000))
        shallow = self._shallow_ok(delimiter)
        floor = marker if marker > prefix else prefix
        # A marker strictly INSIDE a collapsed subtree must re-surface
        # that subtree's common prefix (S3 semantics). The deep stream
        # does this naturally (later keys re-collapse); the shallow
        # stream holds ONE entry per subtree, sorted before such a
        # marker — widen the page scan floor back to it.
        page_floor, floor_left = marker, False
        if shallow and marker and marker.startswith(prefix):
            di = marker[len(prefix):].find("/")
            if di >= 0:
                cp = marker[:len(prefix) + di + 1]
                if cp != marker:
                    page_floor, floor_left = cp, True
        walk = self.metacache.walk_for(
            self, bucket, prefix, shallow=shallow,
            seek=page_floor if floor_left else marker)
        if walk.truncated and walk.done and walk.keys and \
                marker >= walk.keys[-1]:
            # Continuing past a capped stream: a start-floored walk
            # (shared by further continuations) keeps pagination
            # moving instead of re-walking into the same cap.
            walk = self.metacache.walk_for(self, bucket, prefix,
                                           start=marker, shallow=shallow)
        need = max_keys + 1
        while True:
            count, done = walk.wait_past(floor, need)
            keys, entries = walk.keys, walk.entries  # append-only; read
            # only indices < count (stable)
            info = ListObjectsInfo()
            seen_prefixes: set[str] = set()
            last_added = ""
            complete = False     # page filled or range exhausted
            if not marker:
                idx = 0
            elif floor_left:
                idx = bisect.bisect_left(keys, page_floor, 0, count)
            else:
                idx = bisect.bisect_right(keys, marker, 0, count)
            for i in range(idx, count):
                path = keys[i]
                if not path.startswith(prefix):
                    if path > prefix and not prefix.startswith(path):
                        complete = True
                        break    # sorted stream passed the prefix range
                    continue
                if delimiter:
                    rest = path[len(prefix):]
                    di = rest.find(delimiter)
                    if di >= 0:
                        cp = prefix + rest[:di + len(delimiter)]
                        # Skip a prefix only when the whole page before
                        # it was already returned; a marker INSIDE the
                        # prefix (start-after=a/1, cp=a/) must still
                        # surface it.
                        if cp in seen_prefixes or (
                                marker and cp <= marker
                                and not (marker.startswith(cp)
                                         and marker != cp)):
                            continue
                        if len(info.objects) + len(seen_prefixes) \
                                >= max_keys:
                            info.is_truncated = True
                            info.next_marker = last_added
                            complete = True
                            break
                        seen_prefixes.add(cp)
                        last_added = cp
                        continue
                entry = entries[i]
                if entry is PREFIX_MARK:
                    continue     # only reachable with a delimiter set
                fis = self._entry_fileinfos(bucket, path, entry)
                if not fis:
                    continue
                fi = fis[0]
                if fi.deleted and not include_versions:
                    continue
                if len(info.objects) + len(seen_prefixes) >= max_keys:
                    info.is_truncated = True
                    info.next_marker = last_added
                    complete = True
                    break
                if include_versions:
                    for v in fis:
                        info.objects.append(
                            self._to_object_info(bucket, path, v))
                else:
                    info.objects.append(
                        self._to_object_info(bucket, path, fi))
                last_added = path
            if complete or done:
                if walk.error is not None and not complete and not keys:
                    raise walk.error
                if done and not complete and walk.truncated:
                    # The stream hit its memory cap before the range
                    # was exhausted: page out what we have; the next
                    # page starts a fresh walk (expensive but correct —
                    # names past the cap must not silently vanish).
                    info.is_truncated = True
                    info.next_marker = last_added or (
                        keys[count - 1] if count else "")
                info.prefixes = sorted(seen_prefixes)
                return info
            # Stream not deep enough to fill the page yet: wait for
            # more entries (delimiter collapse can consume many raw
            # entries per returned prefix).
            need *= 2

    def list_versions_all(self, bucket: str, object_: str) -> list[FileInfo]:
        results, _ = self._fanout(
            [lambda d=d: d.list_versions(bucket, object_) for d in self.disks])
        for r in results:
            if r:
                return r
        raise ObjectNotFound(bucket, object_)


def _resolve_range(spec: tuple, size: int, bucket: str, object_: str):
    """(start|None, end|None) -> (offset, length), HTTP Range semantics."""
    lo, hi = spec
    if lo is None:                       # suffix: last `hi` bytes
        if hi is None or hi <= 0:
            raise InvalidRange(bucket, object_)
        start = max(0, size - hi)
        return start, size - start
    if lo >= size:
        raise InvalidRange(bucket, object_)
    if hi is None:
        return lo, size - lo
    if lo > hi:
        raise InvalidRange(bucket, object_)
    return lo, min(hi, size - 1) - lo + 1


def _join_chunks(chunks) -> bytes:
    """Flatten a per-drive framed chunk list to one bytes object."""
    if len(chunks) == 1:
        return bytes(chunks[0])
    return b"".join(bytes(c) for c in chunks)


def _clean_user_meta(meta: dict) -> dict:
    """Strip keys that would collide with the internal metadata
    namespace — a client must not be able to inject or clobber SSE
    parameters via x-amz-meta-x-internal-* headers."""
    return {k: v for k, v in meta.items()
            if not k.startswith("x-internal-")}


def _parity_matrix(k: int, m: int) -> np.ndarray:
    from minio_tpu.ops import gf256
    return gf256.parity_matrix(k, m)


def _swallow(fn):
    try:
        fn()
    except Exception:  # noqa: BLE001
        pass


def _unwrap_disk(d):
    """Innermost drive behind health/test wrappers (each exposes
    `wrapped`), bounded against pathological self-wrapping."""
    for _ in range(8):
        inner = getattr(d, "wrapped", None)
        if inner is None:
            return d
        d = inner
    return d


def _group_commit_capable(d) -> bool:
    """True when `d` implements the batched commit protocol in a way
    the group lanes may use. The health wrapper forwards; LocalStorage
    and CrashDisk define commit_group on their type; anything else
    (remote drives, NaughtyDisk — whose targeted fault injection a
    forwarded commit_group would silently bypass) keeps the solo
    fan-out. OfflineDisk slots pass: every op on them fails the same
    way solo ops do."""
    for _ in range(8):
        if d is None:
            return False
        cls = type(d)
        if cls.__name__ == "OfflineDisk":
            return True
        if "commit_group" in cls.__dict__:
            return True
        if cls.__name__ == "DiskHealthWrapper":
            d = d.wrapped
            continue
        return False
    return False


def _leased_fns(fns, lease):
    """Wrap per-drive fan-out callables so each holds its own reference
    on `lease` until its op truly completes: fan-out collection may
    abandon a future on deadline while the drive worker is still
    reading the pooled memory, and an unreferenced buffer recycled
    under a live reader is silent shard corruption. Each wrapper
    releases exactly once, in the worker's own thread. (A wrapper that
    never runs — engine shed, pre-expired deadline — parks its
    reference until GC, where the pool's leak net returns and counts
    it.) No-op when lease is None."""
    if lease is None:
        return fns
    out = []
    for fn in fns:
        if fn is None:
            out.append(None)
            continue
        lease.retain()

        def run(fn=fn):
            try:
                return fn()
            finally:
                lease.release()
        out.append(run)
    return out


def _raise_for_quorum(errors, exc, quorum=None, ok=None):
    """Quorum-failure triage: surface DeadlineExceeded (-> 408
    RequestTimeout) only when the REQUEST's budget was DECISIVE — had
    the deadline-cut drives been given time and succeeded, `quorum`
    could have been met. When genuine drive faults alone preclude
    quorum, the honest verdict stays the 503 quorum error: masking
    real cluster unhealth as a client timeout would hide it from
    operators and send clients into retry loops."""
    deadline_cut = sum(isinstance(e, DeadlineExceeded) for e in errors)
    if deadline_cut:
        if ok is None:
            ok = sum(e is None for e in errors)
        if quorum is None or ok + deadline_cut >= quorum:
            raise DeadlineExceeded(
                "request deadline exceeded before quorum")
    raise exc
