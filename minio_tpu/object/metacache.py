"""Metacache: shared listing walk streams with write invalidation.

The analogue of the reference's metacache subsystem
(cmd/metacache.go:55-70, cmd/metacache-set.go:700,
cmd/metacache-walk.go:73): a listing starts ONE background walk of the
erasure set — per-drive sorted journal walks, k-way merged, each key
quorum-resolved — whose sorted entry stream accumulates in memory and
persists in blocks on the set's first drive. Every page of that
listing, every concurrent listing of the same prefix, and every
follow-up listing within the reuse window serves from the SAME stream:
a 50k-object bucket walks once, not once per page.

Invalidation is generation-based: any namespace mutation in the bucket
bumps its generation, orphaning walks started before it (correctness
first — a cached stream can never serve names from before a change).
In distributed mode the `on_bump` hook broadcasts the bump to peer
nodes (grid/peers KIND_LISTING) with leading-edge coalescing, so a
peer's next listing after a remote write re-walks immediately instead
of waiting out a TTL. Persisted blocks additionally let a RESTARTED
process warm its first listing from the previous run's walk when the
bucket has been quiet (age-bounded — a crash loses only cache, never
correctness).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Optional

# Entries per persisted block.
_BLOCK = 4096
# A completed walk is reusable this long after its last touch; an
# ACTIVE walk is always reusable (generation still governs validity).
_IDLE_TTL = 30.0
# Persisted-walk warm-start window for a fresh process: the same 2 s
# cross-restart staleness contract the bucket-metadata cache uses.
_PERSIST_TTL = 2.0
# Per-bucket leading-edge coalescing window for peer bump broadcasts.
_BUMP_COALESCE = 0.1
# Cap on in-memory entries per walk (~100 MB worst case); beyond it the
# walk marks itself truncated and later listings fall back to fresh
# walks — bounded memory beats completeness here.
_MAX_ENTRIES = 500_000

META_DIR = "listcache"         # under SYS_VOL on the first drive
SYS_VOL_ = ".mtpu.sys"


class WalkStream:
    """One background merged+resolved walk of (bucket, prefix)."""

    def __init__(self, bucket: str, prefix: str, gen: int,
                 start: str = ""):
        self.bucket = bucket
        self.prefix = prefix
        # Walks normally start at the prefix; a continuation PAST a
        # truncated stream's cap starts at that listing's marker so
        # pagination always progresses.
        self.start_after = start
        self.gen = gen
        self.keys: list[str] = []          # sorted walked keys
        self.maps: list[list] = []         # per-key resolved version maps
        self.done = False
        self.error: Optional[Exception] = None
        self.truncated = False             # hit _MAX_ENTRIES
        self.last_touch = time.monotonic()
        self.cond = threading.Condition()
        self._cancel = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- production (walk thread) --------------------------------------

    def start(self, es) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(es,), daemon=True,
            name=f"metacache-walk-{self.bucket}")
        self._thread.start()

    def _run(self, es) -> None:
        try:
            for path, maps in es._walk_resolved(
                    self.bucket, self.prefix, self.start_after):
                if self._cancel.is_set():
                    # Orphaned by a bump/eviction: stop burning drive
                    # I/O and memory on a stream nobody can read.
                    self.truncated = True
                    break
                with self.cond:
                    self.keys.append(path)
                    self.maps.append(maps)
                    self.cond.notify_all()
                    if len(self.keys) >= _MAX_ENTRIES:
                        self.truncated = True
                        break
            if not self.truncated:
                self._persist(es)
        except Exception as e:  # noqa: BLE001 - reported to waiters
            self.error = e
        finally:
            with self.cond:
                self.done = True
                self.cond.notify_all()

    def _persist(self, es) -> None:
        """Write the completed stream to the first drive in blocks so a
        restarted process can warm-start (best-effort)."""
        import json

        import msgpack
        if not es.disks:
            return
        d = es.disks[0]
        base = f"{META_DIR}/{_safe(self.bucket)}/{_safe(self.prefix)}"
        try:
            for i in range(0, max(len(self.keys), 1), _BLOCK):
                blob = msgpack.packb(
                    list(zip(self.keys[i:i + _BLOCK],
                             self.maps[i:i + _BLOCK])))
                d.write_all(SYS_VOL_, f"{base}/blk-{i // _BLOCK:06d}",
                            blob)
            d.write_all(SYS_VOL_, f"{base}/head", json.dumps({
                "created_ns": time.time_ns(),
                "blocks": (len(self.keys) + _BLOCK - 1) // _BLOCK,
                "count": len(self.keys)}).encode())
        except Exception:  # noqa: BLE001 - cache persistence is optional
            pass

    @classmethod
    def load_persisted(cls, es, bucket: str, prefix: str,
                       gen: int) -> Optional["WalkStream"]:
        """A previous process's completed walk, if fresh enough."""
        import json

        import msgpack
        if not es.disks:
            return None
        d = es.disks[0]
        base = f"{META_DIR}/{_safe(bucket)}/{_safe(prefix)}"
        try:
            head = json.loads(d.read_all(SYS_VOL_, f"{base}/head"))
            if time.time_ns() - head["created_ns"] > _PERSIST_TTL * 1e9:
                return None
            w = cls(bucket, prefix, gen)
            for i in range(head["blocks"]):
                for path, maps in msgpack.unpackb(
                        d.read_all(SYS_VOL_, f"{base}/blk-{i:06d}")):
                    w.keys.append(path)
                    w.maps.append(maps)
            if len(w.keys) != head["count"]:
                return None
            w.done = True
            return w
        except Exception:  # noqa: BLE001 - absent / stale / corrupt
            return None

    def cancel(self) -> None:
        self._cancel.set()
        with self.cond:
            self.cond.notify_all()

    # -- consumption (listing threads) ---------------------------------

    def wait_past(self, key: str, need: int, timeout: float = 60.0):
        """Block until the walk has produced `need` entries strictly
        after `key` (or finished); returns (count, done) — a stable
        VIEW bound: keys/maps are append-only, so indices below count
        never change and readers need no copy (a full-list snapshot
        per page would make pagination of a big walk quadratic)."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while True:
                idx = bisect.bisect_right(self.keys, key)
                if self.done or len(self.keys) - idx >= need:
                    self.last_touch = time.monotonic()
                    return (len(self.keys), self.done)
                left = deadline - time.monotonic()
                if left <= 0:
                    return (len(self.keys), self.done)
                self.cond.wait(timeout=min(left, 5))


def _safe(s: str) -> str:
    import hashlib
    return hashlib.sha256(s.encode()).hexdigest()[:24]


class MetaCache:
    """Per-erasure-set walk-stream registry + bucket generations."""

    MAX_WALKS = 8

    def __init__(self):
        self._mu = threading.Lock()
        self._gen: dict[str, int] = {}            # bucket -> generation
        self._walks: dict[tuple, WalkStream] = {}  # (bucket,prefix) -> walk
        self.hits = 0
        self.misses = 0
        # Distributed boot installs a broadcaster(bucket) here; bumps
        # fan out to peers with leading-edge coalescing.
        self.on_bump: Optional[Callable] = None
        self._last_broadcast: dict[str, float] = {}
        self._pending_broadcast: set[str] = set()
        # Local bump listeners (no coalescing, fired on EVERY bump —
        # including broadcast=False pulls from peers/workers): bump is
        # the one funnel every namespace mutation already goes through,
        # so caches that must see writes (object/fi_cache) subscribe
        # here instead of wiring each mutation call site.
        self.listeners: list[Callable[[str], None]] = []

    def generation(self, bucket: str) -> int:
        with self._mu:
            return self._gen.get(bucket, 0)

    def bump(self, bucket: str, broadcast: bool = True) -> None:
        """Any namespace mutation in the bucket orphans its walks."""
        for listener in self.listeners:
            try:
                listener(bucket)
            except Exception:  # noqa: BLE001 - listeners are best-effort
                pass
        defer = 0.0
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for k in [k for k in self._walks if k[0] == bucket]:
                w = self._walks.pop(k, None)
                if w is not None:
                    w.cancel()
            cb = self.on_bump
            now = time.monotonic()
            if cb is not None and broadcast:
                last = self._last_broadcast.get(bucket, 0.0)
                if now - last < _BUMP_COALESCE:
                    # Coalesce the burst, but GUARANTEE a trailing
                    # broadcast — dropping it would leave peers stale
                    # after the burst's last write until their next
                    # fresh walk.
                    if bucket in self._pending_broadcast:
                        cb = None
                    else:
                        self._pending_broadcast.add(bucket)
                        defer = _BUMP_COALESCE - (now - last)
                else:
                    self._last_broadcast[bucket] = now
        if cb is None or not broadcast:
            return
        if defer > 0:
            def fire():
                with self._mu:
                    self._pending_broadcast.discard(bucket)
                    self._last_broadcast[bucket] = time.monotonic()
                try:
                    cb(bucket)
                except Exception:  # noqa: BLE001 - best-effort
                    pass
            t = threading.Timer(defer, fire)
            t.daemon = True
            t.start()
            return
        try:
            cb(bucket)
        except Exception:  # noqa: BLE001 - peer fan-out best-effort
            pass

    def drop_bucket(self, bucket: str) -> None:
        for listener in self.listeners:
            try:
                listener(bucket)
            except Exception:  # noqa: BLE001 - listeners are best-effort
                pass
        with self._mu:
            self._gen.pop(bucket, None)
            self._last_broadcast.pop(bucket, None)
            for k in [k for k in self._walks if k[0] == bucket]:
                w = self._walks.pop(k, None)
                if w is not None:
                    w.cancel()

    def walk_for(self, es, bucket: str, prefix: str,
                 start: str = "") -> WalkStream:
        """Find-or-start the shared walk of (bucket, prefix) at the
        current generation; concurrent and follow-up listings share it
        (reference: cmd/metacache-set.go lookup before starting a new
        listing)."""
        with self._mu:
            gen = self._gen.get(bucket, 0)
            key = (bucket, prefix, start)
            w = self._walks.get(key)
            now = time.monotonic()
            cancelled = w is not None and w._cancel.is_set()
            if w is not None and w.gen == gen and w.error is None and \
                    not cancelled and \
                    (not w.done or now - w.last_touch < _IDLE_TTL):
                # Truncated-but-complete walks are still served: pages
                # below the cap come from them, and the listing layer
                # requests a start-floored continuation walk for pages
                # past it (a blanket rejection would livelock huge
                # buckets re-walking into the same cap forever).
                self.hits += 1
                return w
            self.misses += 1
            w = None
            if gen == 0 and not start:
                # Quiet bucket, fresh process: a recent persisted walk
                # warm-starts the first listing.
                w = WalkStream.load_persisted(es, bucket, prefix, gen)
            if w is None:
                w = WalkStream(bucket, prefix, gen, start=start)
                w.start(es)
            self._walks[key] = w
            while len(self._walks) > self.MAX_WALKS:
                oldest = min(self._walks,
                             key=lambda k: self._walks[k].last_touch)
                evicted = self._walks.pop(oldest)
                if evicted is not None:
                    evicted.cancel()
            return w
