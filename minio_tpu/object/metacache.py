"""Metacache: shared listing-page cache with write invalidation.

The analogue (scoped down) of the reference's metacache
(cmd/metacache.go:55-70, cmd/metacache-set.go:700): the reference
persists listing walk streams and shares them between concurrent
listers; here, resolved listing PAGES are cached in a bounded LRU keyed
by the exact listing parameters and stamped with the bucket's mutation
GENERATION — any object write/delete in the bucket bumps the
generation, so a cached page can never serve names or metadata from
before a change (correctness first; the win is the common hot pattern
of dashboards and SDKs re-issuing identical listings against a quiet
bucket, which previously re-walked a drive majority every time).
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class MetaCache:
    """Per-erasure-set listing page cache.

    Generation bumps catch every mutation made through THIS process's
    set object; in distributed mode a peer node writes shard files over
    the storage RPC without touching this layer, so a short TTL bounds
    cross-node staleness (the same 2 s contract the bucket-metadata and
    IAM caches use)."""

    MAX_PAGES = 256
    TTL = 2.0

    def __init__(self):
        self._mu = threading.Lock()
        self._gen: dict[str, int] = {}           # bucket -> generation
        self._pages: OrderedDict = OrderedDict()  # key -> (gen, ts, page)
        self.hits = 0
        self.misses = 0

    def generation(self, bucket: str) -> int:
        with self._mu:
            return self._gen.get(bucket, 0)

    def bump(self, bucket: str) -> None:
        """Any namespace mutation in the bucket invalidates every
        cached page for it (lazily, via the generation stamp)."""
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1

    def get(self, bucket: str, key: tuple):
        import time
        with self._mu:
            hit = self._pages.get(key)
            if hit is None or hit[0] != self._gen.get(bucket, 0) or \
                    time.monotonic() - hit[1] > self.TTL:
                self.misses += 1
                return None
            self._pages.move_to_end(key)
            self.hits += 1
            return hit[2]

    def put(self, bucket: str, key: tuple, page,
            gen: int = -1) -> None:
        """`gen`: the generation read BEFORE the walk began. A write
        concurrent with the walk bumps past it, so the page stores with
        the stale stamp and the next get() misses — stamping the
        CURRENT generation would mark a possibly-incomplete page
        fresh."""
        import time
        with self._mu:
            if gen < 0:
                gen = self._gen.get(bucket, 0)
            self._pages[key] = (gen, time.monotonic(), page)
            self._pages.move_to_end(key)
            while len(self._pages) > self.MAX_PAGES:
                self._pages.popitem(last=False)

    def drop_bucket(self, bucket: str) -> None:
        """Bucket deletion: the generation map must not pin memory for
        names that no longer exist."""
        with self._mu:
            self._gen.pop(bucket, None)
            self._pages = OrderedDict(
                (k, v) for k, v in self._pages.items() if k[0] != bucket)
