"""Metacache: shared listing walk streams with write invalidation.

The analogue of the reference's metacache subsystem
(cmd/metacache.go:55-70, cmd/metacache-set.go:700,
cmd/metacache-walk.go:73): a listing starts ONE background walk of the
erasure set — per-drive sorted journal walks, k-way merged, each key
quorum-resolved — whose sorted entry stream accumulates in memory and
persists on the set's first drive. Every page of that listing, every
concurrent listing of the same prefix, and every follow-up listing
within the reuse window serves from the SAME stream: a 50k-object
bucket walks once, not once per page.

Stream entries are TRIMMED: the common case is a native-scanned
summary tuple (storage/meta_scan) holding only the fields listings
need, not a full parsed journal — at 10M objects the difference is
gigabytes of dict trees. Entry classes:

    ("s", vlist)   trimmed per-version summary tuples
    ("m", maps)    full version maps (scanner fallback, metadata past
                   the summary, quorum-resolved disagreements)
    PREFIX_MARK    shallow (delimiter) walks: a key prefix marker

Persistence (format v2): a completed walk writes fixed-size sorted
SEGMENTS plus a head carrying a first/last-key index per segment, so a
continuation page in a fresh process SEEKS to its marker's segment
instead of re-reading the whole stream, and a truncated walk's
continuation walk COMPACTS in place onto the base run (appended
segments + updated index) once it goes idle. A restarted process
warm-starts from persisted runs inside MTPU_META_PERSIST_TTL (default
2 s — the same cross-restart staleness contract the bucket-metadata
cache uses; raising it trades a wider unclean-handoff staleness window
for more warm starts).

Invalidation is generation-based: any namespace mutation in the bucket
bumps its generation, orphaning walks started before it (correctness
first — a cached stream can never serve names from before a change).
In distributed mode the `on_bump` hook broadcasts the bump to peer
nodes (grid/peers KIND_LISTING) with leading-edge coalescing.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Callable, Optional

from minio_tpu.storage.meta_scan import PREFIX_MARK


def _env_num(key: str, default, cast=float):
    try:
        v = cast(os.environ.get(key, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


# Entries per persisted segment (the prefix index is one (first, last,
# count) triple per segment, so seeks are O(log segments) + one segment
# read).
_SEG = _env_num("MTPU_META_SEG_ENTRIES", 4096, int)
# A completed walk is reusable this long after its last touch; an
# ACTIVE walk is always reusable (generation still governs validity).
_IDLE_TTL = 30.0
# Persisted-walk warm-start window for a fresh process.
_PERSIST_TTL = _env_num("MTPU_META_PERSIST_TTL", 2.0)
# Per-bucket leading-edge coalescing window for peer bump broadcasts.
_BUMP_COALESCE = 0.1
# Cap on in-memory entries per walk; beyond it the walk marks itself
# truncated and later pages continue via start-floored walks — bounded
# memory beats completeness here.
_MAX_ENTRIES = _env_num("MTPU_META_MAX_ENTRIES", 500_000, int)

META_DIR = "listcache"         # under SYS_VOL on the first drive
SYS_VOL_ = ".mtpu.sys"
_FMT = 2


def _canon_entry(e):
    """Canonical in-memory form of a (possibly msgpack-round-tripped)
    stream entry: summaries are tuples-of-tuples, markers are THE
    module sentinel."""
    if isinstance(e, (list, tuple)):
        if len(e) == 1 and e[0] == PREFIX_MARK[0]:
            return PREFIX_MARK
        if len(e) == 2 and e[0] == "s":
            return ("s", tuple(tuple(v) for v in e[1]))
        if len(e) == 2 and e[0] == "m":
            return ("m", list(e[1]))
    return None


class WalkStream:
    """One background merged+resolved walk of (bucket, prefix)."""

    def __init__(self, bucket: str, prefix: str, gen: int,
                 start: str = "", shallow: bool = False):
        self.bucket = bucket
        self.prefix = prefix
        # Walks normally start at the prefix; a continuation PAST a
        # truncated stream's cap starts at that listing's marker so
        # pagination always progresses.
        self.start_after = start
        self.shallow = shallow
        self.gen = gen
        self.keys: list[str] = []          # sorted walked keys
        self.entries: list = []            # per-key stream entries
        self.done = False
        self.error: Optional[Exception] = None
        self.truncated = False             # hit _MAX_ENTRIES
        self.persisted_from = 0            # segments skipped by a seek
        # Bypass walks (coherence gate down) are unregistered — no
        # registry dedupe means concurrent ephemeral walks of one
        # (bucket, prefix) exist, and letting them persist would
        # interleave their seg/head writes into a torn base run.
        self.ephemeral = False
        self.last_touch = time.monotonic()
        self.cond = threading.Condition()
        self._cancel = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- production (walk thread) --------------------------------------

    def start(self, es, mc: Optional["MetaCache"] = None) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(es, mc), daemon=True,
            name=f"metacache-walk-{self.bucket}")
        self._thread.start()

    def _run(self, es, mc) -> None:
        try:
            for path, entry in es._walk_resolved(
                    self.bucket, self.prefix, self.start_after,
                    shallow=self.shallow):
                if self._cancel.is_set():
                    # Orphaned by a bump/eviction: stop burning drive
                    # I/O and memory on a stream nobody can read.
                    self.truncated = True
                    break
                with self.cond:
                    self.keys.append(path)
                    self.entries.append(entry)
                    self.cond.notify_all()
                    if len(self.keys) >= _MAX_ENTRIES:
                        self.truncated = True
                        break
            if not self._cancel.is_set() and not self.shallow \
                    and not self.ephemeral and not self.start_after:
                # BASE runs persist BEFORE done: a caller that saw the
                # walk complete may immediately restart-warm-start from
                # the segments (test-asserted), and the base write has
                # no wait in it.
                self._persist(es, mc)
        except Exception as e:  # noqa: BLE001 - reported to waiters
            self.error = e
        finally:
            with self.cond:
                self.done = True
                self.cond.notify_all()
        # CONTINUATION runs compact AFTER signalling done: compaction
        # may wait out a bounded gap-retry (_compact_onto) for an
        # earlier continuation to land, and that wait must never delay
        # a listing page blocked on this stream's completion.
        if self.error is None and not self._cancel.is_set() \
                and not self.shallow and not self.ephemeral \
                and self.start_after:
            self._persist(es, mc)

    # -- persistence (format v2: segments + prefix index) --------------

    @staticmethod
    def _dir(bucket: str, prefix: str) -> str:
        return f"{META_DIR}/{_safe(bucket)}/{_safe(prefix)}"

    def _persist(self, es, mc) -> None:
        """Write the completed stream to the first drive as fixed-size
        sorted segments + an indexed head (best-effort). Continuation
        walks COMPACT onto the base run in place when contiguous;
        without a base to extend they are not persisted."""
        import msgpack
        if not es.disks or not self.keys:
            return
        d = es.disks[0]
        base = self._dir(self.bucket, self.prefix)
        try:
            if self.start_after:
                self._compact_onto(d, base, mc)
                return
            seg_index = []
            for s, i in enumerate(range(0, len(self.keys), _SEG)):
                keys = self.keys[i:i + _SEG]
                blob = msgpack.packb(
                    list(zip(keys, self.entries[i:i + _SEG])))
                d.write_all(SYS_VOL_, f"{base}/seg-{s:06d}", blob)
                seg_index.append([keys[0], keys[-1], len(keys)])
            d.write_all(SYS_VOL_, f"{base}/head", json.dumps({
                "v": _FMT,
                "created_ns": time.time_ns(),
                "count": len(self.keys),
                "start": "",
                "truncated": self.truncated,
                "seg": seg_index}).encode())
        except Exception:  # noqa: BLE001 - cache persistence is optional
            pass

    # Gap-retry window: a continuation floored past the base's current
    # end waits this long for the earlier continuation (whose append
    # closes the gap) to land before giving up.
    _COMPACT_WAIT = 5.0

    def _compact_onto(self, d, base: str, mc) -> None:
        """Append this continuation stream's entries to the persisted
        base run (segments + index updated in place; the head rewrite
        is the commit point — a crash leaves stray seg files that the
        head's count check ignores).

        Continuations complete in COMPLETION order, not key order: a
        later page's walk can finish before an earlier page's. A walk
        floored at or below the base's current end appends only its
        tail past the end (boundary dedup); one floored ABOVE it would
        persist a run with a silent key-range HOLE — it waits (bounded)
        for the earlier continuation to close the gap, then appends.
        Compactions of one MetaCache serialize on compact_mu so two
        walks never interleave their read-modify-write of the head."""
        import contextlib
        import msgpack
        lock = mc.compact_mu if mc is not None else contextlib.nullcontext()
        deadline = time.monotonic() + self._COMPACT_WAIT
        while not self._cancel.is_set():
            with lock:
                try:
                    head = json.loads(d.read_all(SYS_VOL_, f"{base}/head"))
                except Exception:  # noqa: BLE001 - no base run to extend
                    return
                if head.get("v") != _FMT or not head.get("truncated") or \
                        not head.get("seg"):
                    return
                last = head["seg"][-1][1]
                if self.start_after <= last:
                    # Boundary dedup: only the tail past the base's end
                    # appends (a start-floored walk re-emits its floor
                    # key; an overlapping walk re-emits the overlap).
                    keys, entries = self.keys, self.entries
                    lo = bisect.bisect_right(keys, last)
                    if lo >= len(keys):
                        return
                    seg_index = list(head["seg"])
                    s = len(seg_index)
                    for i in range(lo, len(keys), _SEG):
                        kseg = keys[i:i + _SEG]
                        blob = msgpack.packb(
                            list(zip(kseg, entries[i:i + _SEG])))
                        d.write_all(SYS_VOL_, f"{base}/seg-{s:06d}", blob)
                        seg_index.append([kseg[0], kseg[-1], len(kseg)])
                        s += 1
                    head.update({
                        "count": head["count"] + len(keys) - lo,
                        "truncated": self.truncated,
                        "seg": seg_index})
                    d.write_all(SYS_VOL_, f"{base}/head",
                                json.dumps(head).encode())
                    if mc is not None:
                        mc.compactions += 1
                    return
            if time.monotonic() > deadline or self._cancel.is_set():
                # Gap never closed — or a bump orphaned this walk
                # mid-wait (its entries predate a mutation and must
                # not reach the persisted run); stay truncated.
                return
            time.sleep(0.05)

    @classmethod
    def load_persisted(cls, es, bucket: str, prefix: str, gen: int,
                       marker: str = "") -> Optional["WalkStream"]:
        """A previous process's persisted run, if fresh enough. With a
        marker, only the segments covering keys past it are read (the
        seek the prefix index exists for); the loaded stream then
        starts at the marker like a start-floored walk would."""
        import msgpack
        if not es.disks:
            return None
        d = es.disks[0]
        base = cls._dir(bucket, prefix)
        try:
            head = json.loads(d.read_all(SYS_VOL_, f"{base}/head"))
            if head.get("v") != _FMT:
                return None
            if time.time_ns() - head["created_ns"] > _PERSIST_TTL * 1e9:
                return None
            seg_index = head.get("seg") or []
            first = 0
            if marker:
                # Seek: skip whole segments whose last key <= marker.
                while first < len(seg_index) and \
                        seg_index[first][1] <= marker:
                    first += 1
                if first >= len(seg_index):
                    return None     # run ends at/before the marker
            w = cls(bucket, prefix, gen, start=marker)
            w.persisted_from = first
            want = 0
            for s in range(first, len(seg_index)):
                want += seg_index[s][2]
                for path, entry in msgpack.unpackb(
                        d.read_all(SYS_VOL_, f"{base}/seg-{s:06d}"),
                        raw=False, strict_map_key=False):
                    entry = _canon_entry(entry)
                    if entry is None:
                        return None
                    w.keys.append(path)
                    w.entries.append(entry)
            if len(w.keys) != want or want == 0:
                return None
            w.truncated = bool(head.get("truncated"))
            w.done = True
            return w
        except Exception:  # noqa: BLE001 - absent / stale / corrupt
            return None

    def cancel(self) -> None:
        self._cancel.set()
        with self.cond:
            self.cond.notify_all()

    # -- consumption (listing threads) ---------------------------------

    def wait_past(self, key: str, need: int, timeout: float = 60.0):
        """Block until the walk has produced `need` entries strictly
        after `key` (or finished); returns (count, done) — a stable
        VIEW bound: keys/entries are append-only, so indices below
        count never change and readers need no copy (a full-list
        snapshot per page would make pagination of a big walk
        quadratic)."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while True:
                idx = bisect.bisect_right(self.keys, key)
                if self.done or len(self.keys) - idx >= need:
                    self.last_touch = time.monotonic()
                    return (len(self.keys), self.done)
                left = deadline - time.monotonic()
                if left <= 0:
                    return (len(self.keys), self.done)
                self.cond.wait(timeout=min(left, 5))


def _safe(s: str) -> str:
    import hashlib
    return hashlib.sha256(s.encode()).hexdigest()[:24]


class MetaCache:
    """Per-erasure-set walk-stream registry + bucket generations."""

    MAX_WALKS = 8

    def __init__(self):
        self._mu = threading.Lock()
        self._gen: dict[str, int] = {}            # bucket -> generation
        self._walks: dict[tuple, WalkStream] = {}  # key -> walk
        # Serializes persisted-run compactions (WalkStream._compact_onto
        # read-modify-writes the segment head from walk threads).
        self.compact_mu = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.persisted_loads = 0
        self.compactions = 0
        self.walks_started = 0
        # Distributed boot installs a broadcaster(bucket) here; bumps
        # fan out to peers with leading-edge coalescing. Coalescing
        # window per instance: the ACKED generation protocol
        # (grid/coherence) sets it to 0 — an invalidation deferred by
        # a timer would open a cross-node staleness window the
        # coherence gate cannot see, so coherence pushes fire
        # synchronously on every bump.
        self.on_bump: Optional[Callable] = None
        self.bump_coalesce: float = _BUMP_COALESCE
        self._last_broadcast: dict[str, float] = {}
        self._pending_broadcast: set[str] = set()
        # Local bump listeners (no coalescing, fired on EVERY bump —
        # including broadcast=False pulls from peers/workers): bump is
        # the one funnel every namespace mutation already goes through,
        # so caches that must see writes (object/fi_cache) subscribe
        # here instead of wiring each mutation call site.
        self.listeners: list[Callable[[str], None]] = []
        # Cross-node coherence gate (grid/coherence.PeerCoherence
        # .coherent on distributed sets; None = local-only, no check).
        # While the gate is down, walk_for orphans cached streams for
        # the requested bucket and re-walks — listings stay correct
        # (drives are the source of truth), just uncached, until the
        # generation resync re-arms the gate.
        self.remote_gate: Optional[Callable[[], bool]] = None

    def generation(self, bucket: str) -> int:
        with self._mu:
            return self._gen.get(bucket, 0)

    def walks_active(self) -> int:
        with self._mu:
            return sum(1 for w in self._walks.values() if not w.done)

    def stats(self) -> dict:
        with self._mu:
            active = sum(1 for w in self._walks.values() if not w.done)
            walks = len(self._walks)
        return {"hits": self.hits, "misses": self.misses,
                "walks_active": active, "walks_cached": walks,
                "walks_started": self.walks_started,
                "persisted_loads": self.persisted_loads,
                "compactions": self.compactions}

    def bump(self, bucket: str, broadcast: bool = True) -> None:
        """Any namespace mutation in the bucket orphans its walks."""
        for listener in self.listeners:
            try:
                listener(bucket)
            except Exception:  # noqa: BLE001 - listeners are best-effort
                pass
        defer = 0.0
        with self._mu:
            self._gen[bucket] = self._gen.get(bucket, 0) + 1
            for k in [k for k in self._walks if k[0] == bucket]:
                w = self._walks.pop(k, None)
                if w is not None:
                    w.cancel()
            cb = self.on_bump
            now = time.monotonic()
            if cb is not None and broadcast and self.bump_coalesce > 0:
                last = self._last_broadcast.get(bucket, 0.0)
                if now - last < self.bump_coalesce:
                    # Coalesce the burst, but GUARANTEE a trailing
                    # broadcast — dropping it would leave peers stale
                    # after the burst's last write until their next
                    # fresh walk.
                    if bucket in self._pending_broadcast:
                        cb = None
                    else:
                        self._pending_broadcast.add(bucket)
                        defer = self.bump_coalesce - (now - last)
                else:
                    self._last_broadcast[bucket] = now
        if cb is None or not broadcast:
            return
        if defer > 0:
            def fire():
                with self._mu:
                    self._pending_broadcast.discard(bucket)
                    self._last_broadcast[bucket] = time.monotonic()
                try:
                    cb(bucket)
                except Exception:  # noqa: BLE001 - best-effort
                    pass
            t = threading.Timer(defer, fire)
            t.daemon = True
            t.start()
            return
        try:
            cb(bucket)
        except Exception:  # noqa: BLE001 - peer fan-out best-effort
            pass

    def drop_bucket(self, bucket: str) -> None:
        for listener in self.listeners:
            try:
                listener(bucket)
            except Exception:  # noqa: BLE001 - listeners are best-effort
                pass
        with self._mu:
            self._gen.pop(bucket, None)
            self._last_broadcast.pop(bucket, None)
            for k in [k for k in self._walks if k[0] == bucket]:
                w = self._walks.pop(k, None)
                if w is not None:
                    w.cancel()

    def walk_for(self, es, bucket: str, prefix: str,
                 start: str = "", shallow: bool = False,
                 seek: str = "") -> WalkStream:
        """Find-or-start the shared walk of (bucket, prefix) at the
        current generation; concurrent and follow-up listings share it
        (reference: cmd/metacache-set.go lookup before starting a new
        listing).

        `seek` is the requesting page's scan floor: on a miss it (a)
        re-uses any COMPLETED stream floored at or below it that still
        covers it, and (b) lets a fresh process's deep continuation
        page load only the persisted segments past it instead of the
        whole run."""
        gate = self.remote_gate
        if gate is not None:
            try:
                ok = bool(gate())
            except Exception:  # noqa: BLE001 - a broken gate fails closed
                ok = False
            if not ok:
                # Incoherent (peer disarmed / no protocol): cached and
                # persisted streams are unprovable — BYPASS the
                # registry with a fresh unregistered walk. Not a bump:
                # bumping per lookup would cancel concurrent listings'
                # in-flight walks (mutual starvation under sustained
                # listings for as long as any peer is down) and churn
                # the fileinfo cache through the bump listeners. The
                # bypass walk serves only this call; the resync that
                # re-arms the gate bumps whatever actually changed.
                with self._mu:
                    self.misses += 1
                    self.walks_started += 1
                    gen = self._gen.get(bucket, 0)
                w = WalkStream(bucket, prefix, gen, start=start,
                               shallow=shallow)
                w.ephemeral = True
                w.start(es, self)
                return w
        with self._mu:
            gen = self._gen.get(bucket, 0)
            key = (bucket, prefix, start, shallow)
            w = self._walks.get(key)
            now = time.monotonic()
            cancelled = w is not None and w._cancel.is_set()
            if w is not None and w.gen == gen and w.error is None and \
                    not cancelled and \
                    (not w.done or now - w.last_touch < _IDLE_TTL):
                # Truncated-but-complete walks are still served: pages
                # below the cap come from them, and the listing layer
                # requests a start-floored continuation walk for pages
                # past it (a blanket rejection would livelock huge
                # buckets re-walking into the same cap forever).
                self.hits += 1
                return w
            if seek and not start:
                # Coverage scan: a done stream floored at/below the
                # page (e.g. an earlier seek-load) serves it directly.
                best = None
                for (b2, p2, _, sh2), cand in self._walks.items():
                    if b2 != bucket or p2 != prefix or sh2 != shallow:
                        continue
                    if cand.gen != gen or cand.error is not None or \
                            cand._cancel.is_set() or not cand.done or \
                            now - cand.last_touch >= _IDLE_TTL:
                        continue
                    if cand.start_after <= seek and \
                            (not cand.truncated
                             or (cand.keys and cand.keys[-1] > seek)) \
                            and (best is None
                                 or cand.start_after > best.start_after):
                        best = cand
                if best is not None:
                    self.hits += 1
                    best.last_touch = now
                    return best
            self.misses += 1
            w = None
            if gen == 0 and not shallow:
                # Quiet bucket, fresh process: a recent persisted run
                # warm-starts the first listing — and SEEKS to the
                # page's segment for deep/continuation pages.
                w = WalkStream.load_persisted(es, bucket, prefix, gen,
                                              marker=start or seek)
                if w is not None:
                    self.persisted_loads += 1
                    key = (bucket, prefix, w.start_after, shallow)
            if w is None:
                w = WalkStream(bucket, prefix, gen, start=start,
                               shallow=shallow)
                self.walks_started += 1
                w.start(es, self)
            self._walks[key] = w
            while len(self._walks) > self.MAX_WALKS:
                oldest = min(self._walks,
                             key=lambda k: self._walks[k].last_touch)
                evicted = self._walks.pop(oldest)
                if evicted is not None:
                    evicted.cancel()
            return w
