"""Hot-object read tier: frequency-admitted whole-object RAM cache.

Millions of users hitting a small hot set should be served from memory
at line rate, not by fanning every GET across the erasure shards and a
journal read. This module pins hot plaintext objects as contiguous
buffers and serves them either straight off the epoll event loop
(s3/eventloop._try_hot, before dispatch — the request never reaches a
handler thread) or from the handler GET path (s3/server._get_object hit
branch) — in both cases without touching the object layer.

Admission is tinyLFU-style (Einziger & Friedman, "TinyLFU: A Highly
Efficient Cache Admission Policy"): a count-min frequency sketch with
4-bit-capped counters and periodic halving estimates each key's recent
popularity; a doorkeeper bloom filter absorbs the first access so
one-hit-wonder scans never increment the sketch, let alone evict the
genuinely hot set. A candidate is admitted only when the cache has
free room or its estimated frequency beats the eviction victim's.
Residency is a segmented LRU (probation/protected): new admits land in
probation, a second hit promotes to protected, eviction drains
probation first — scan resistance on the residency side too.

Coherence rides the exact funnel object/fi_cache.py uses, so
invalidation is already exact cluster-wide:

- every namespace mutation (PUT/DELETE/copy/group-commit batch/peer
  bump pull) goes through ``metacache.bump`` → our bucket listener
  drops the bucket synchronously, before any member acks;
- the token protocol (``token()`` before the read fan-out, checked in
  ``put()``) makes inserts race-free against concurrent mutations;
- pre-forked workers observe the shared ``list.gen`` bump file (their
  own SharedGen instance — ``changed()`` is stateful per observer) and
  flush wholesale when a sibling worker mutated anything;
- on distributed sets, hits gate on the OWNING sets' coherence only
  (each pool's deterministic hash slot for the key, via
  ``fi_cache.remote_gate`` — grid/coherence.PeerCoherence.coherent, or
  the deny-all sentinel on bare remote sets): an unrelated set's
  partition no longer blanks the whole read tier. A set observed
  down-then-recovered gets its OWN entries selectively flushed before
  its hits resume (bumps broadcast during the gap never reached us);
  the walk is dynamic so elastic pool expansion is picked up live, and
  a topology change still flushes wholesale.

Kill switch: ``MTPU_HOT_CACHE=off`` (or 0/false) disables admission
and lookups wholesale; responses are byte-identical either way because
a hit replays the handler's own captured header bytes (Date re-spliced
per second) and the miss path is untouched.

Knobs: ``MTPU_HOT_CACHE_MAX`` (entry cap, default 1024),
``MTPU_HOT_CACHE_BYTES`` (resident-byte cap, default 256 MiB),
``MTPU_HOT_CACHE_OBJ_MAX`` (per-object size cap, default 8 MiB).
"""
from __future__ import annotations

import email.utils
import os
import re
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Optional

# Process-wide registry of live caches so the coherence plane
# (grid/coherence.make_set_invalidator's bucket=="" wildcard) can flush
# every cache in the process without holding a server reference.
_REGISTRY: "weakref.WeakSet[HotObjectCache]" = weakref.WeakSet()


def flush_process_caches() -> None:
    """Flush every live HotObjectCache in this process (wildcard
    cross-node invalidations, topology changes)."""
    for cache in list(_REGISTRY):
        try:
            cache.invalidate_all()
        except Exception:  # noqa: BLE001 - flush is best-effort
            pass


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Date splice: a cached hit replays the exact header bytes the handler
# produced on the admitting miss, with only the Date value re-stamped.
# http.server's send_response writes Date via email.utils.formatdate
# (usegmt) — producing ours with the same function keeps the hit
# byte-identical in format to a fresh miss.

_DATE_RE = re.compile(rb"\r\nDate: [^\r\n]*\r\n")
_date_cached: tuple[int, bytes] = (0, b"")


def date_bytes() -> bytes:
    """Current RFC 1123 date, encoded, cached per wall-clock second."""
    global _date_cached
    now = time.time()
    sec = int(now)
    cached = _date_cached
    if cached[0] == sec:
        return cached[1]
    d = email.utils.formatdate(now, usegmt=True).encode("ascii")
    _date_cached = (sec, d)
    return d


def split_head(head: bytes) -> Optional[tuple[bytes, bytes]]:
    """Split captured response-head bytes around the Date value.

    Returns (prefix, suffix) where prefix ends with ``b"Date: "`` and
    suffix starts with the ``\\r\\n`` that terminated the date line, or
    None when no Date header is present (template unusable)."""
    m = _DATE_RE.search(head)
    if m is None:
        return None
    return head[:m.start()] + b"\r\nDate: ", head[m.end() - 2:]


# ---------------------------------------------------------------------------
# TinyLFU admission filter.

class FrequencySketch:
    """Count-min sketch with 4-bit-capped counters, a doorkeeper bloom
    filter in front, and periodic halving (aging) so the estimate
    tracks *recent* frequency.

    The doorkeeper absorbs each key's first occurrence: a pure scan of
    one-hit wonders only ever sets doorkeeper bits, leaving the sketch
    untouched — their estimate stays ~1 and never beats a resident
    victim's, which is the scan resistance TinyLFU is for."""

    ROWS = 4
    CAP = 15  # 4-bit counters, stored one per byte for simplicity

    def __init__(self, max_entries: int):
        width = 64
        while width < 4 * max(16, max_entries):
            width <<= 1
        self._width = width
        self._mask = width - 1
        self._rows = [bytearray(width) for _ in range(self.ROWS)]
        self._door = bytearray(width // 8)
        # Aging: after a sample window of increments, halve everything
        # and reset the doorkeeper so stale popularity decays.
        self._sample = 10 * max(16, max_entries)
        self._increments = 0
        self._seed = id(self) & 0xFFFF

    def _index(self, row: int, key: str) -> int:
        return hash((self._seed, row, key)) & self._mask

    def _door_probe(self, key: str) -> tuple[int, int, int, int]:
        h = hash((self._seed, -1, key))
        a = h & self._mask
        b = (h >> 17) & self._mask
        return a >> 3, 1 << (a & 7), b >> 3, 1 << (b & 7)

    def _door_has(self, key: str) -> bool:
        i1, m1, i2, m2 = self._door_probe(key)
        return bool(self._door[i1] & m1) and bool(self._door[i2] & m2)

    def record(self, key: str) -> None:
        """Count one occurrence of key (access or candidacy)."""
        if not self._door_has(key):
            i1, m1, i2, m2 = self._door_probe(key)
            self._door[i1] |= m1
            self._door[i2] |= m2
            return
        for row in range(self.ROWS):
            r = self._rows[row]
            i = self._index(row, key)
            if r[i] < self.CAP:
                r[i] += 1
        self._increments += 1
        if self._increments >= self._sample:
            self._age()

    def estimate(self, key: str) -> int:
        est = min(self._rows[row][self._index(row, key)]
                  for row in range(self.ROWS))
        if self._door_has(key):
            est += 1
        return est

    def _age(self) -> None:
        for r in self._rows:
            for i in range(self._width):
                r[i] >>= 1
        self._door = bytearray(self._width // 8)
        self._increments //= 2


class _Entry:
    __slots__ = ("info", "body", "head_prefix", "head_suffix", "nbytes")

    def __init__(self, info: Any, body: bytes):
        self.info = info
        self.body = body
        # Captured response-head template (split around Date) — None
        # until the handler back-fills it on an eligible miss; the
        # event-loop short circuit only engages once it exists.
        self.head_prefix: Optional[bytes] = None
        self.head_suffix: Optional[bytes] = None
        self.nbytes = len(body)


class HotObjectCache:
    """Per-process whole-object read cache with tinyLFU admission.

    Thread-safe; every public method takes the internal lock. The data
    stored per entry is the *plaintext served body* (bytes, immutable —
    the event loop writes memoryviews over it with zero copies) plus
    the ObjectInfo it was served with and, once captured, the response
    head template for the loop short-circuit."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "MTPU_HOT_CACHE", "").lower() not in ("0", "off", "false")
        self.max_entries = max(1, _env_int("MTPU_HOT_CACHE_MAX", 1024))
        self.max_bytes = max(1, _env_int("MTPU_HOT_CACHE_BYTES",
                                         256 * 1024 * 1024))
        self.obj_max = max(1, _env_int("MTPU_HOT_CACHE_OBJ_MAX",
                                       8 * 1024 * 1024))
        self._mu = threading.Lock()
        # Segmented LRU: MRU at the OrderedDict tail. Keys: (bucket, key).
        self._probation: "OrderedDict[tuple[str, str], _Entry]" = \
            OrderedDict()
        self._protected: "OrderedDict[tuple[str, str], _Entry]" = \
            OrderedDict()
        self._protected_cap = max(1, (self.max_entries * 4) // 5)
        self._bytes = 0
        self._sketch = FrequencySketch(self.max_entries)
        # Token protocol (same contract as fi_cache): per-bucket
        # generation, bumped by invalidation; put() refuses when the
        # generation moved between token() and put().
        self._gens: dict[str, int] = {}
        # Pre-forked workers: shared-generation observer over the
        # fleet's list.gen bump file (io/workers.attach wires an
        # instance OF OUR OWN — changed() is stateful per observer).
        self.shared_gen: Optional[Any] = None
        # The object layer we front; _serving() walks its sets live so
        # elastic pool changes and per-set coherence gates are honored
        # without a static snapshot.
        self._layer: Optional[Any] = None
        self._wired_ids: set[int] = set()
        self._wired_count = -1
        # Sets whose coherence gate we observed DOWN and have not yet
        # recovery-flushed (by id — sets aren't hashable on content).
        self._down_ids: set[int] = set()
        # Counters (stats(), metrics).
        self.hits = 0
        self.misses = 0
        self.admits = 0
        self.rejects = 0
        self.evictions = 0
        self.invalidations = 0
        _REGISTRY.add(self)

    # -- topology / coherence -------------------------------------------

    def attach_layer(self, layer: Any) -> None:
        """Front the given object layer: subscribe to every set's
        metacache bump funnel and honor its coherence gates."""
        self._layer = layer
        with self._mu:
            self._wire_sets_locked()

    @staticmethod
    def _layer_sets(layer: Any) -> list:
        # Local mirror of metrics.layer_sets (object/ must not import
        # the s3 front end): pools of sets, a sets list, or a bare set.
        if layer is None:
            return []
        pools = getattr(layer, "pools", None)
        if pools:
            out = []
            for pool in pools:
                out.extend(getattr(pool, "sets", None) or [pool])
            return out
        sets = getattr(layer, "sets", None)
        if sets:
            return list(sets)
        return [layer]

    def _wire_sets_locked(self) -> bool:
        """Subscribe our bucket invalidator to any set not yet wired.
        Returns True when the topology changed since the last walk."""
        sets = self._layer_sets(self._layer)
        changed = (len(sets) != self._wired_count)
        for s in sets:
            if id(s) in self._wired_ids:
                continue
            mc = getattr(s, "metacache", None)
            listeners = getattr(mc, "listeners", None)
            if listeners is not None:
                listeners.append(self.invalidate_bucket)
            self._wired_ids.add(id(s))
        self._wired_count = len(sets)
        return changed

    def _owning_sets(self, object_: str) -> Optional[list]:
        """The sets that could hold this key — one per pool, each
        pool's deterministic hash slot. None when the layer shape
        doesn't expose pool routing (gate on every set instead)."""
        pools = getattr(self._layer, "pools", None)
        if not pools:
            return None
        out = []
        for p in pools:
            sets = getattr(p, "sets", None)
            idx_fn = getattr(p, "set_index", None)
            if not sets or idx_fn is None:
                return None
            try:
                out.append(sets[idx_fn(object_)])
            except Exception:  # noqa: BLE001 - unknown routing: gate all
                return None
        return out

    def _serving(self, object_: Optional[str] = None) -> bool:
        """True when a hit for `object_` may be served right now.
        Walks the layer's sets live: wires newly-appeared sets
        (elastic pools — a topology change flushes first), then
        requires the OWNING sets' coherence gates (every set when no
        key / no pool routing) to answer coherent, failing closed on
        any error. Partial coherence serves: only the key's own sets
        gate its hit, so one partitioned set doesn't blank the tier.
        A set observed down then coherent again gets its own entries
        selectively flushed before its hits resume — bumps broadcast
        while it was incoherent never reached us."""
        if not self.enabled:
            return False
        self.maybe_flush()
        with self._mu:
            if self._wire_sets_locked() and (self._probation
                                             or self._protected):
                self._invalidate_all_locked()
        sets = None if object_ is None else self._owning_sets(object_)
        if sets is None:
            sets = self._layer_sets(self._layer)
        ok = True
        recovered = []
        for s in sets:
            gate = getattr(getattr(s, "fi_cache", None), "remote_gate",
                           None)
            if gate is None:
                continue
            try:
                up = bool(gate())
            except Exception:  # noqa: BLE001 - gate errors = incoherent
                up = False
            if not up:
                self._down_ids.add(id(s))
                ok = False
            elif id(s) in self._down_ids:
                recovered.append(s)
        for s in recovered:
            self._flush_set(s)
            self._down_ids.discard(id(s))
        return ok

    def _flush_set(self, target: Any) -> None:
        """Recovery flush for ONE set: drop only the entries some pool
        routes to `target`, bumping their buckets' generations so an
        in-flight put() racing this flush is refused. Entries owned by
        other, continuously-coherent sets stay hot."""
        pools = getattr(self._layer, "pools", None)
        with self._mu:
            if not pools:
                self._invalidate_all_locked()
                return
            doomed: list = []
            for seg in (self._probation, self._protected):
                for key in seg:
                    owned = False
                    for p in pools:
                        try:
                            if p.sets[p.set_index(key[1])] is target:
                                owned = True
                                break
                        except Exception:  # noqa: BLE001 - doom it
                            owned = True
                            break
                    if owned:
                        doomed.append((seg, key))
            for seg, key in doomed:
                self._bytes -= seg.pop(key).nbytes
            for bucket in {key[0] for _, key in doomed}:
                self._gens[bucket] = self._gens.get(bucket, 0) + 1
            if doomed:
                self.invalidations += 1

    def maybe_flush(self) -> None:
        """Flush wholesale when a sibling worker process bumped the
        shared generation (any mutation anywhere in the fleet)."""
        sg = self.shared_gen
        if sg is not None:
            try:
                if sg.changed():
                    self.invalidate_all()
            except Exception:  # noqa: BLE001 - observer errors = flush
                self.invalidate_all()

    # -- token protocol (fi_cache contract) -----------------------------

    def token(self, bucket: str) -> int:
        """Current generation for bucket; take BEFORE the read fan-out
        and hand to put(). setdefault (not get) so a concurrent
        invalidation that bumps the generation is always observed as a
        mismatch by put()."""
        self.maybe_flush()
        with self._mu:
            return self._gens.setdefault(bucket, 0)

    # -- lookups --------------------------------------------------------

    def get(self, bucket: str, object_: str) -> Optional[_Entry]:
        """Resident entry for (bucket, object) or None. Counts the
        access in the admission sketch either way; a probation hit
        promotes to protected."""
        if not self._serving(object_):
            return None
        key = (bucket, object_)
        with self._mu:
            self._sketch.record(bucket + "/" + object_)
            entry = self._protected.get(key)
            if entry is not None:
                self._protected.move_to_end(key)
                self.hits += 1
                return entry
            entry = self._probation.get(key)
            if entry is None:
                self.misses += 1
                return None
            # Second hit: promote, demoting the protected LRU back to
            # probation when the protected segment is full.
            del self._probation[key]
            self._protected[key] = entry
            if len(self._protected) > self._protected_cap:
                old_key, old = self._protected.popitem(last=False)
                self._probation[old_key] = old
                self._probation.move_to_end(old_key)
            self.hits += 1
            return entry

    # -- admission / insert ---------------------------------------------

    def admit(self, bucket: str, object_: str, size: int) -> bool:
        """Should the GET path buffer this object for insertion?

        Free room admits outright (warm-up); otherwise tinyLFU: the
        candidate must beat the eviction victim's estimated frequency.
        The doorkeeper means a first-ever access never wins a
        contested admission."""
        if not self.enabled or size <= 0 or size > self.obj_max:
            return False
        with self._mu:
            key = (bucket, object_)
            if key in self._probation or key in self._protected:
                return False
            if (len(self._probation) + len(self._protected)
                    < self.max_entries
                    and self._bytes + size <= self.max_bytes):
                return True
            victim_key = next(iter(self._probation), None) \
                or next(iter(self._protected), None)
            if victim_key is None:
                self.rejects += 1
                return False
            skey = bucket + "/" + object_
            vkey = victim_key[0] + "/" + victim_key[1]
            if self._sketch.estimate(skey) > self._sketch.estimate(vkey):
                return True
            self.rejects += 1
            return False

    def put(self, bucket: str, object_: str, info: Any, body: bytes,
            head: Optional[bytes], token: int) -> bool:
        """Insert a served object. Refused when the bucket generation
        moved since token() — a mutation raced the read and the bytes
        may predate it."""
        if not self.enabled or len(body) > self.obj_max:
            return False
        with self._mu:
            if self._gens.get(bucket, 0) != token:
                return False
            key = (bucket, object_)
            old = self._probation.pop(key, None) \
                or self._protected.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            entry = _Entry(info, body)
            if head is not None:
                tpl = split_head(head)
                if tpl is not None:
                    entry.head_prefix, entry.head_suffix = tpl
            self._probation[key] = entry
            self._bytes += entry.nbytes
            self.admits += 1
            self._evict_locked()
            return True

    def set_head(self, bucket: str, object_: str, etag: str,
                 version_id: str, head: bytes) -> None:
        """Back-fill the response-head template on an entry that was
        admitted without one (e.g. first hit came through the handler
        path). Identity-checked so a template from a different object
        generation can never be spliced onto newer bytes."""
        with self._mu:
            key = (bucket, object_)
            entry = self._protected.get(key) or self._probation.get(key)
            if entry is None or entry.head_prefix is not None:
                return
            if (getattr(entry.info, "etag", None) != etag
                    or (getattr(entry.info, "version_id", None) or "")
                    != (version_id or "")):
                return
            tpl = split_head(head)
            if tpl is not None:
                entry.head_prefix, entry.head_suffix = tpl

    def _evict_locked(self) -> None:
        while (len(self._probation) + len(self._protected)
               > self.max_entries or self._bytes > self.max_bytes):
            if self._probation:
                _, victim = self._probation.popitem(last=False)
            elif self._protected:
                _, victim = self._protected.popitem(last=False)
            else:
                break
            self._bytes -= victim.nbytes
            self.evictions += 1

    # -- invalidation ----------------------------------------------------

    def invalidate_bucket(self, bucket: str) -> None:
        """Metacache bump listener: every namespace mutation in the
        bucket lands here synchronously, before the mutation acks."""
        with self._mu:
            self._gens[bucket] = self._gens.get(bucket, 0) + 1
            for seg in (self._probation, self._protected):
                for key in [k for k in seg if k[0] == bucket]:
                    self._bytes -= seg.pop(key).nbytes
            self.invalidations += 1

    def invalidate_all(self) -> None:
        with self._mu:
            self._invalidate_all_locked()

    def _invalidate_all_locked(self) -> None:
        for bucket in self._gens:
            self._gens[bucket] += 1
        self._probation.clear()
        self._protected.clear()
        self._bytes = 0
        self.invalidations += 1

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "entries": len(self._probation) + len(self._protected),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "obj_max": self.obj_max,
                "hits": self.hits,
                "misses": self.misses,
                "admits": self.admits,
                "rejects": self.rejects,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }
