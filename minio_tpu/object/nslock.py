"""Namespace locking: per-(bucket, object) reader/writer locks.

The analogue of the reference's nsLockMap (cmd/namespace-lock.go:157-231):
every mutating object operation (put/delete/heal/multipart-commit) takes
the write lock for its key, reads take the read lock, so concurrent
overwrite+delete+heal of one key serialize instead of landing different
versions on different drives. Entries are refcounted and removed when the
last holder releases, exactly like the reference's map hygiene.

In distributed mode the same interface is backed by dsync quorum locks
(reference: distLockInstance, cmd/namespace-lock.go:157); local mode uses
an in-process RW lock (reference: localLockInstance + internal/lsync).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class LockTimeout(Exception):
    """Lock could not be acquired within the deadline (mapped to the
    S3 'OperationTimedOut' family by the front-end)."""


class _RWLock:
    """Writer-preferring reader/writer lock with timeouts."""

    __slots__ = ("_cond", "_readers", "_writer", "_writers_waiting", "ref")

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self.ref = 0  # guarded by the owning map's mutex

    def acquire_read(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            # Block behind waiting writers so a put storm cannot starve out
            # (the reference's lsync spins with the same writer preference).
            while self._writer or self._writers_waiting:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return False
            self._readers += 1
            return True

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class NSLockMap:
    """Refcounted map of (volume, path) -> RW lock."""

    DEFAULT_TIMEOUT = 60.0

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._locks: dict[tuple[str, str], _RWLock] = {}

    def _enter(self, key: tuple[str, str]) -> _RWLock:
        with self._mu:
            lk = self._locks.get(key)
            if lk is None:
                lk = self._locks[key] = _RWLock()
            lk.ref += 1
            return lk

    def _exit(self, key: tuple[str, str], lk: _RWLock) -> None:
        with self._mu:
            lk.ref -= 1
            if lk.ref == 0:
                self._locks.pop(key, None)

    @contextmanager
    def write(self, volume: str, path: str,
              timeout: float = DEFAULT_TIMEOUT):
        key = (volume, path)
        lk = self._enter(key)
        try:
            if not lk.acquire_write(timeout):
                raise LockTimeout(f"write lock {volume}/{path}")
            try:
                yield
            finally:
                lk.release_write()
        finally:
            self._exit(key, lk)

    @contextmanager
    def read(self, volume: str, path: str,
             timeout: float = DEFAULT_TIMEOUT):
        key = (volume, path)
        lk = self._enter(key)
        try:
            if not lk.acquire_read(timeout):
                raise LockTimeout(f"read lock {volume}/{path}")
            try:
                yield
            finally:
                lk.release_read()
        finally:
            self._exit(key, lk)
