"""Pool rebalance: drain overfilled pools toward the cluster average.

The analogue of the reference's erasure-server-pool rebalancing
(cmd/erasure-server-pool-rebalance.go:100 rebalanceMeta + rebalanceStart
/ rebalanceStatus / rebalanceStop admin verbs): decommission's other
half. Where decommission empties a pool completely and takes it out of
placement, rebalance keeps every pool in service and moves just enough
objects from pools ABOVE the average fill fraction into the emptier
ones that the cluster converges — the operation an operator runs after
adding a new (empty) expansion pool.

Mechanics shared with decommission (object/decom.py):
- the per-key transfer primitive `migrate_key` (snapshot -> restore
  newest-first -> locked verify/cleanup), so reads stay correct at
  every moment and concurrent overwrites/deletes never resurrect;
- checkpointed resume: progress (per-pool bucket/marker/bytes) persists
  to a quorum of pool-0 drives every CHECKPOINT_EVERY keys; a killed
  server resumes where it stopped (the reference persists
  rebalanceMeta in .minio.sys/rebalance.meta the same way).

Differences from decommission, matching the reference:
- sources stay IN placement (new writes still follow most-free-space,
  which naturally prefers the destinations);
- each participating pool has its own walk state and byte target
  (stop when the pool reaches the average), reference's per-pool
  rebalance workers;
- destinations exclude the other participating sources so bytes never
  ping-pong between two overfilled pools.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from minio_tpu.object.decom import (LeaseHeld, MigrationGovernor,
                                    coordinator_lease, migrate_key,
                                    page_dispatcher)
from minio_tpu.storage.local import SYS_VOL

__all__ = ["Rebalance", "RebalanceError", "LeaseHeld", "load_state",
           "bucket_used_bytes", "pool_usage"]

REBAL_PATH = "config/rebalance.json"
CHECKPOINT_EVERY = 16
# A pool participates when its used bytes exceed its capacity-weighted
# share of the cluster's data by this RELATIVE margin (reference uses a
# small hysteresis band too, so a balanced cluster is a no-op).
# Relative to the pool's target usage — not to raw capacity — so the
# criterion behaves the same for a 1 MiB test corpus and a 1 PiB one.
THRESHOLD = 0.02


class RebalanceError(Exception):
    pass


def bucket_used_bytes(layer, bucket: str) -> int:
    """Sum of all version sizes in one bucket via a paged walk — the
    shared accounting loop behind rebalance planning and quota
    enforcement's live fallback."""
    used = 0
    marker = ""
    while True:
        page = layer.list_objects(bucket, marker=marker, max_keys=1000,
                                  include_versions=True)
        used += sum(o.size for o in page.objects)
        if not page.is_truncated:
            break
        marker = page.next_marker
    return used


def pool_usage(pool) -> tuple[int, int]:
    """(used_bytes, capacity_bytes) for one pool. Used bytes come from
    walking the namespace (version stacks included) — the same
    accounting the scanner keeps; capacity from the drives."""
    used = sum(bucket_used_bytes(pool, b.name) for b in pool.list_buckets())
    cap = 0
    for s in pool.sets:
        for d in s.disks:
            try:
                info = d.disk_info()
                cap += info.total
            except Exception:  # noqa: BLE001 - offline drive
                pass
    return used, cap


def load_state(pools_layer) -> Optional[dict]:
    """Highest-revision persisted rebalance state across pool-0 drives
    (quorum-voted), or None."""
    votes: dict[bytes, int] = {}
    for s in pools_layer.pools[0].sets:
        for d in s.disks:
            try:
                blob = d.read_all(SYS_VOL, REBAL_PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
    best: Optional[dict] = None
    for blob in votes:
        try:
            doc = json.loads(blob)
        except ValueError:
            continue
        if isinstance(doc, dict) and "pools" in doc and \
                (best is None or doc.get("rev", 0) > best.get("rev", 0)):
            best = doc
    return best


class Rebalance:
    """One cluster rebalance run (fresh or resumed)."""

    def __init__(self, pools_layer, state: Optional[dict] = None,
                 checkpoint_every: int = CHECKPOINT_EVERY,
                 threshold: float = THRESHOLD):
        if len(pools_layer.pools) < 2:
            raise RebalanceError("rebalance needs at least two pools")
        self.layer = pools_layer
        self.checkpoint_every = checkpoint_every
        self.threshold = threshold
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lease = None
        # Planning walks every pool's namespace for usage accounting —
        # that happens in the background worker, NOT here: the admin
        # start handler must return immediately on large clusters.
        self.state = state or {"status": "planning",
                               "started_ns": time.time_ns(),
                               "pools": {}, "rev": 0, "yields": 0}
        self.state.setdefault("yields", 0)
        self._gov = MigrationGovernor(pools_layer, self.state, self._stop)

    # -- planning -------------------------------------------------------

    def _plan(self) -> dict:
        usages = [pool_usage(p) for p in self.layer.pools]
        total_used = sum(u for u, _ in usages)
        total_cap = sum(c for _, c in usages) or 1
        avg = total_used / total_cap
        pools = {}
        for i, (used, cap) in enumerate(usages):
            cap = cap or 1
            # This pool's capacity-weighted share of the cluster data.
            target_used = avg * cap
            participating = used > target_used * (1 + self.threshold) \
                and used > 0 and i not in self.layer.decommissioning
            # Bytes this pool must shed to land on the average.
            target = max(0, int(used - target_used)) if participating else 0
            pools[str(i)] = {
                "participating": participating,
                "used": used, "capacity": cap,
                "bytes_target": target, "bytes_moved": 0,
                "bucket": "", "marker": "", "done": not participating,
                "migrated": 0, "failed": 0,
            }
        return {"status": "rebalancing", "started_ns": time.time_ns(),
                "pools": pools, "rev": 0}

    # -- persistence ----------------------------------------------------

    def _persist(self) -> None:
        self.state["rev"] = self.state.get("rev", 0) + 1
        self.state["checkpoint_ns"] = time.time_ns()
        blob = json.dumps(self.state, sort_keys=True).encode()
        disks = [d for s in self.layer.pools[0].sets for d in s.disks]
        ok = 0
        for d in disks:
            try:
                d.write_all(SYS_VOL, REBAL_PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(disks) // 2 + 1:
            raise RebalanceError("could not persist rebalance state")

    # -- control --------------------------------------------------------

    def _acquire_lease(self) -> None:
        """One coordinator fleet-wide: see decom.coordinator_lease.
        Quorum loss mid-run pauses this driver (checkpoint persists,
        status stays 'rebalancing') so the next lease winner resumes."""
        lease = coordinator_lease(self.layer, "rebalance")
        if lease is not None:
            lease.on_lost = self._stop.set
            if not lease.lock(write=True, timeout=5.0):
                raise LeaseHeld(
                    "rebalance coordinator lease held by another node")
        self._lease = lease

    def _release_lease(self) -> None:
        lease, self._lease = self._lease, None
        if lease is not None:
            try:
                lease.unlock()
            except Exception:  # noqa: BLE001 - lease may be lost already
                pass

    def start(self) -> None:
        self._acquire_lease()
        self.state.pop("paused", None)
        try:
            self._persist()
        except RebalanceError:
            self._release_lease()
            raise
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rebalance")
        self._thread.start()

    def stop(self) -> None:
        """Pause (state stays 'rebalancing'; a resume continues from
        the checkpoint)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._release_lease()
        if self.state.get("status") in ("planning", "rebalancing"):
            # Explicit pause (vs crash): the elastic janitor only
            # auto-resumes walks that never set this flag.
            self.state["paused"] = True
            try:
                self._persist()
            except RebalanceError:
                pass

    def wait(self, timeout: float = 300) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    # -- the walk -------------------------------------------------------

    def _sources(self) -> list[int]:
        return [int(i) for i, rec in self.state["pools"].items()
                if rec["participating"] and not rec["done"]]

    def _pick_dst(self, exclude: set[int]) -> int:
        best, best_free = None, -1
        for i, p in enumerate(self.layer.pools):
            if i in exclude or i in self.layer.decommissioning:
                continue
            free = p.free_space()
            if free > best_free:
                best, best_free = i, free
        if best is None:
            raise RebalanceError("no destination pool available")
        return best

    def _run(self) -> None:
        try:
            if self.state.get("status") == "planning":
                plan = self._plan()
                plan["started_ns"] = self.state["started_ns"]
                self.state.update(plan)
                self._persist()
            sources = set(self._sources())
            for src in sorted(sources):
                if self._stop.is_set():
                    return
                self._drain_pool(src, exclude=sources)
            if self._stop.is_set():
                return
            failed = sum(r["failed"] for r in self.state["pools"].values())
            self.state["status"] = "failed" if failed else "complete"
            self.state["finished_ns"] = time.time_ns()
            self._persist()
        except Exception as e:  # noqa: BLE001 - recorded, resumable
            self.state["status"] = "failed"
            self.state["error"] = str(e)
            try:
                self._persist()
            except RebalanceError:
                pass
        finally:
            self._release_lease()

    def _do_key(self, src: int, rec: dict, bucket: str, key: str,
                size: int, exclude: set[int]) -> None:
        """Gate on foreground pressure, migrate one key, account it
        (governor counters are thread-safe for workers > 1)."""
        gov = self._gov
        if not gov.gate():
            return
        try:
            migrate_key(self.layer, src, bucket, key,
                        lambda: self._pick_dst(exclude))
            gov.add(rec, "migrated")
            gov.add(rec, "bytes_moved", size)
        except Exception as e:  # noqa: BLE001 - keep going
            gov.add(rec, "failed")
            rec["last_error"] = f"{bucket}/{key}: {e}"

    def _drain_pool(self, src: int, exclude: set[int]) -> None:
        from concurrent.futures import ThreadPoolExecutor
        rec = self.state["pools"][str(src)]
        pool = self.layer.pools[src]
        gov = self._gov
        since_ckpt = 0
        # Fleet-sharded walk (see decom.PageDispatcher): pages spread
        # across peer nodes; this coordinator aggregates counters and
        # owns every checkpoint.
        disp = page_dispatcher(self.layer)
        workers = ThreadPoolExecutor(
            max_workers=gov.workers,
            thread_name_prefix=f"rebal{src}-mig") \
            if disp is None and gov.workers > 1 else None
        try:
            buckets = sorted(b.name for b in pool.list_buckets())
            start_bucket = rec.get("bucket", "")
            for bucket in buckets:
                if bucket < start_bucket:
                    continue
                marker = rec.get("marker", "") \
                    if bucket == start_bucket else ""
                while not self._stop.is_set():
                    page = pool.list_objects(bucket, marker=marker,
                                             max_keys=256,
                                             include_versions=True)
                    sizes: dict[str, int] = {}
                    for o in page.objects:
                        sizes[o.name] = sizes.get(o.name, 0) + o.size
                    keys = sorted(sizes)
                    if disp is not None:
                        for batch, agg in disp.iter_batches(
                                src, bucket, keys,
                                exclude=exclude | {src}, gate=gov.gate):
                            gov.add(rec, "migrated", agg["migrated"])
                            gov.add(rec, "failed", agg["failed"])
                            gov.add(rec, "bytes_moved", agg["bytes"])
                            if agg.get("last_error"):
                                rec["last_error"] = agg["last_error"]
                            rec["bucket"] = bucket
                            rec["marker"] = batch[-1]
                            since_ckpt += len(batch)
                            if since_ckpt >= self.checkpoint_every:
                                since_ckpt = 0
                                self._persist()
                            if rec["bytes_moved"] >= rec["bytes_target"]:
                                rec["done"] = True
                                self._persist()
                                return
                    elif workers is not None:
                        # Page-barrier parallel migration (see
                        # Decommission._drain): the marker advances
                        # only past a FULLY completed page and the
                        # byte-target check runs at the barrier.
                        list(workers.map(
                            lambda k: self._do_key(src, rec, bucket, k,
                                                   sizes[k], exclude),
                            keys))
                        if keys and not self._stop.is_set():
                            rec["bucket"] = bucket
                            rec["marker"] = keys[-1]
                            since_ckpt += len(keys)
                        if since_ckpt >= self.checkpoint_every:
                            since_ckpt = 0
                            self._persist()
                        if rec["bytes_moved"] >= rec["bytes_target"]:
                            rec["done"] = True
                            self._persist()
                            return
                    else:
                        for key in keys:
                            if self._stop.is_set():
                                return
                            self._do_key(src, rec, bucket, key,
                                         sizes[key], exclude)
                            rec["bucket"] = bucket
                            rec["marker"] = key
                            since_ckpt += 1
                            if since_ckpt >= self.checkpoint_every:
                                since_ckpt = 0
                                self._persist()
                            if rec["bytes_moved"] >= rec["bytes_target"]:
                                # Pool reached the average: done.
                                rec["done"] = True
                                self._persist()
                                return
                    if not page.is_truncated:
                        break
                    marker = page.next_marker
                if self._stop.is_set():
                    return
                rec["bucket"] = bucket
                rec["marker"] = ""
                self._persist()
        finally:
            if workers is not None:
                workers.shutdown(wait=True)
        # Walked everything (targets were estimates): done either way.
        rec["done"] = True
        self._persist()
