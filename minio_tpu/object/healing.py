"""Healing: reconstruct missing/corrupt shards onto bad drives, plus the
MRF ("most recently failed") retry queue.

The analogue of the reference's healing stack (cmd/erasure-healing.go:296
healObject; cmd/mrf.go MRF queue): classify per-drive state for the
quorum version, rebuild ALL n shards from any k readable ones
(reference: Erasure.Heal reconstructs data+parity,
cmd/erasure-decode.go:317), and commit the rebuilt shards to the bad
drives through the same staged rename path writes use. Partial writes
enqueue onto the MRF queue for immediate background repair, exactly the
reference's write-path MRF hook (cmd/erasure-object.go:1556-1594).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Optional

import numpy as np

from minio_tpu.erasure.codec import ceil_frac
from minio_tpu.object.types import ObjectNotFound, ReadQuorumError
from minio_tpu.storage import bitrot
from minio_tpu.storage.meta import FileInfo, FileNotFoundErr, VersionNotFoundErr

DRIVE_STATE_OK = "ok"
DRIVE_STATE_OFFLINE = "offline"
DRIVE_STATE_MISSING = "missing"
DRIVE_STATE_OUTDATED = "outdated"
DRIVE_STATE_CORRUPT = "corrupt"


@dataclasses.dataclass
class HealResult:
    bucket: str
    object: str
    version_id: str = ""
    before: list = dataclasses.field(default_factory=list)
    after: list = dataclasses.field(default_factory=list)
    healed: int = 0
    data_blocks: int = 0
    parity_blocks: int = 0
    size: int = 0                 # logical object bytes (bulk-heal stats)


class HealError(Exception):
    pass


def heal_object(es, bucket: str, object_: str, version_id: str = "",
                deep: bool = False) -> HealResult:
    """Heal one version of one object across the set's drives.

    Serialized against put/delete/get via the namespace write lock
    (reference: healObject's NSLock, cmd/erasure-healing.go:323) so a
    background heal cannot race an in-flight write into purging freshly
    committed shards.

    `deep=False` (the scanner's normal mode) classifies shard files by
    stat (existence + exact framed size) without reading them;
    `deep=True` reads and bitrot-verifies every block (reference scanMode
    normal vs deep, cmd/erasure-healing.go:296).
    """
    from minio_tpu.utils import tracing
    with tracing.op_span("heal", "heal.object",
                         {"bucket": bucket, "object": object_,
                          "deep": int(deep)}), \
            es.ns.write(bucket, object_):
        result = _heal_object_locked(es, bucket, object_, version_id, deep)
    if result.healed:
        # Drive journals changed under this key: cached quorum
        # fileinfo (here and, via the shared generation, in sibling
        # pre-forked workers) must re-resolve or reads would keep an
        # out-of-date holder map past the heal.
        es.metacache.bump(bucket)
    return result


def _heal_object_locked(es, bucket: str, object_: str, version_id: str,
                        deep: bool) -> HealResult:
    from minio_tpu.object import erasure_object as eo

    fis, errors = es._read_version_all(bucket, object_, version_id,
                                       read_data=True)
    n = len(es.disks)
    not_found = sum(isinstance(e, (FileNotFoundErr, VersionNotFoundErr))
                    for e in errors)
    if not_found > n // 2:
        # Majority verdict: this version does not exist. Purge stale
        # copies only when they can NEVER satisfy read quorum again —
        # not-found must exceed the version's parity count, not just a
        # majority (reference deleteIfDangling's stricter criteria,
        # cmd/erasure-object.go:484: a quorum-thin but valid write must
        # heal, not vanish).
        stale = [i for i in range(n) if fis[i] is not None]
        purge = False
        if stale:
            # Parity bound from the most redundant DATA version held by
            # any stale drive (a delete marker has no erasure info and
            # must not collapse the bound to a bare majority).
            ks = [fis[i].erasure.data_blocks for i in stale
                  if not fis[i].deleted and fis[i].erasure.data_blocks]
            if ks:
                m = n - min(ks)
                purge = not_found > max(n // 2, m)
            else:
                # Only delete markers / metadata-only versions: majority
                # not-found is already decisive.
                purge = True
        if stale and purge:
            es._fanout([
                (lambda i=i: _purge_version(es.disks[i], bucket, object_,
                                            fis[i].version_id))
                if i in stale else None for i in range(n)])
            es.metacache.bump(bucket)
        result = HealResult(bucket=bucket, object=object_,
                            version_id=version_id)
        result.before = [DRIVE_STATE_OUTDATED if i in stale
                         else DRIVE_STATE_MISSING for i in range(n)]
        if purge:
            result.after = [DRIVE_STATE_MISSING] * n
            result.healed = len(stale)
        else:
            result.after = list(result.before)
        return result
    any_fi = next((f for f in fis if f is not None), None)
    if any_fi is None:
        raise ObjectNotFound(bucket, object_)
    quorum = max(any_fi.erasure.data_blocks, n // 2) \
        if any_fi.erasure.data_blocks else n // 2 + 1
    fi, _ = es._quorum_fileinfo(fis, quorum)
    if fi is None:
        raise ReadQuorumError(bucket, object_)
    if fi.deleted:
        # Delete markers heal by metadata replication only.
        return _heal_metadata_only(es, bucket, object_, fi, fis, errors)
    from minio_tpu.object.tier import META_TIER
    if (fi.metadata or {}).get(META_TIER):
        # Transitioned versions hold no local data — their shard files
        # were reclaimed at transition; only the metadata pointer
        # replicates (treating the absent data files as damage would
        # 'reconstruct' garbage or purge a healthy version).
        return _heal_metadata_only(es, bucket, object_, fi, fis, errors)

    from minio_tpu.storage.meta import ObjectPartInfo
    k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
    e = es._erasure(k, m)
    shard_size = e.shard_size()
    inline = fi.inline_data is not None
    dist = fi.erasure.distribution
    parts = fi.parts or [ObjectPartInfo(number=1, size=fi.size,
                                        actual_size=fi.size)]

    # Classify drives + load verified shards PER PART (multipart objects
    # store one independently-encoded shard file per part).
    states: list[str] = [DRIVE_STATE_OFFLINE] * n
    # part_shards[part_idx][shard_idx] -> bytes or None
    part_shards: list[list[Optional[np.ndarray]]] = \
        [[None] * (k + m) for _ in parts]

    def load_all_parts(disk_idx: int) -> Optional[list[np.ndarray]]:
        d = es.disks[disk_idx]
        dfi = fis[disk_idx]
        out = []
        try:
            for p in parts:
                plen = e.shard_file_size(p.size)
                if inline:
                    blob = dfi.inline_data or b""
                else:
                    blob = d.read_file(
                        bucket, f"{object_}/{fi.data_dir}/part.{p.number}")
                # Batched bitrot verify: all of this shard file's full
                # blocks hash in one pass, routed through the batched
                # device verify (the get-route batcher, k=1 members)
                # when this host's decode calibration picks the device
                # — deep heal reads whole shard files, the best-case
                # batch, and the drive-replacement bulk heal fans one
                # load per drive so shard files coalesce cross-drive.
                arr = es._verify_shard_blob(blob, shard_size, plen)
                if arr is None:
                    return None
                out.append(arr)
            return out
        except Exception:  # noqa: BLE001 - treat as corrupt
            return None

    def stat_all_parts(disk_idx: int) -> bool:
        """Non-deep check: every shard file exists with the exact
        bitrot-framed size (no data read, no hash verify)."""
        d = es.disks[disk_idx]
        dfi = fis[disk_idx]
        try:
            for p in parts:
                plen = e.shard_file_size(p.size)
                want = bitrot.shard_file_size(plen, shard_size)
                if inline:
                    if len(dfi.inline_data or b"") != want:
                        return False
                else:
                    st = d.stat_info_file(
                        bucket, f"{object_}/{fi.data_dir}/part.{p.number}")
                    if st.st_size != want:
                        return False
            return True
        except Exception:  # noqa: BLE001 - unstattable == corrupt
            return False

    for i in range(n):
        dfi = fis[i]
        if isinstance(errors[i], (FileNotFoundErr, VersionNotFoundErr)):
            states[i] = DRIVE_STATE_MISSING
            continue
        if dfi is None:
            states[i] = DRIVE_STATE_OFFLINE
            continue
        if (dfi.mod_time, dfi.data_dir) != (fi.mod_time, fi.data_dir) \
                or dfi.deleted != fi.deleted:
            states[i] = DRIVE_STATE_OUTDATED
            continue
        if fi.size == 0:
            states[i] = DRIVE_STATE_OK
            for ps in part_shards:
                ps[dist[i] - 1] = np.zeros(0, np.uint8)
            continue
        if deep:
            loaded = load_all_parts(i)
            if loaded is None:
                states[i] = DRIVE_STATE_CORRUPT
            else:
                states[i] = DRIVE_STATE_OK
                for pi, arr in enumerate(loaded):
                    part_shards[pi][dist[i] - 1] = arr
        else:
            states[i] = DRIVE_STATE_OK if stat_all_parts(i) \
                else DRIVE_STATE_CORRUPT

    result = HealResult(bucket=bucket, object=object_,
                        version_id=fi.version_id, before=list(states),
                        data_blocks=k, parity_blocks=m, size=fi.size)
    bad = [i for i in range(n) if states[i] in
           (DRIVE_STATE_MISSING, DRIVE_STATE_OUTDATED, DRIVE_STATE_CORRUPT)]
    if not bad:
        result.after = list(states)
        return result

    if fi.size > 0 and not deep:
        # Non-deep mode deferred the reads; pull verified shards from the
        # stat-OK drives now that a rebuild is actually needed. A drive
        # that passed stat but fails bitrot on read demotes to corrupt.
        ok_idxs = [i for i in range(n) if states[i] == DRIVE_STATE_OK]
        loads, _ = es._fanout([
            (lambda i=i: load_all_parts(i)) if i in ok_idxs else None
            for i in range(n)])
        for i in ok_idxs:
            loaded = loads[i]
            if loaded is None:
                states[i] = DRIVE_STATE_CORRUPT
                result.before[i] = DRIVE_STATE_CORRUPT
                bad.append(i)
            else:
                for pi, arr in enumerate(loaded):
                    part_shards[pi][dist[i] - 1] = arr

    if fi.size > 0:
        for ps in part_shards:
            if sum(1 for s in ps if s is not None) < k:
                raise ReadQuorumError(bucket, object_,
                                      "not enough shards to heal")
            # Rebuild ALL shards (data + parity) of this part.
            e.decode_data_and_parity_blocks(ps)

    # Write rebuilt shards to the bad drives via the staged commit path.
    def heal_one(disk_idx: int):
        d = es.disks[disk_idx]
        shard_idx = dist[disk_idx] - 1
        hfi = dataclasses.replace(
            fi, metadata=dict(fi.metadata), parts=list(fi.parts),
            erasure=dataclasses.replace(fi.erasure, index=shard_idx + 1),
            inline_data=None)
        if fi.size == 0:
            hfi.inline_data = b"" if inline else None
            d.write_metadata(bucket, object_, hfi)
            return
        if inline:
            hfi.inline_data = bitrot.frame_shard(
                part_shards[0][shard_idx], shard_size)
            d.write_metadata(bucket, object_, hfi)
        else:
            staging = eo.new_staging()
            for pi, p in enumerate(parts):
                framed = bitrot.frame_shard(part_shards[pi][shard_idx],
                                            shard_size)
                d.create_file(eo.SYS_VOL,
                              f"{staging}/{fi.data_dir}/part.{p.number}",
                              framed)
            d.rename_data(eo.SYS_VOL, staging, hfi, bucket, object_)

    _, herrs = es._fanout([
        (lambda i=i: heal_one(i)) if i in bad else None
        for i in range(n)])
    after = list(states)
    for i in bad:
        if herrs[i] is None:
            after[i] = DRIVE_STATE_OK
            result.healed += 1
    result.after = after
    return result


def _purge_version(disk, bucket: str, object_: str, version_id: str) -> None:
    try:
        disk.delete_version(bucket, object_, version_id)
    except Exception:  # noqa: BLE001 - best effort purge
        pass


def _heal_metadata_only(es, bucket, object_, fi: FileInfo, fis, errors):
    n = len(es.disks)
    states = []
    for i in range(n):
        if fis[i] is not None and fis[i].mod_time == fi.mod_time \
                and fis[i].deleted == fi.deleted:
            states.append(DRIVE_STATE_OK)
        elif isinstance(errors[i], (FileNotFoundErr, VersionNotFoundErr)):
            states.append(DRIVE_STATE_MISSING)
        else:
            states.append(DRIVE_STATE_OUTDATED if fis[i] is not None
                          else DRIVE_STATE_OFFLINE)
    result = HealResult(bucket=bucket, object=object_,
                        version_id=fi.version_id, before=list(states))
    bad = [i for i in range(n) if states[i] in (DRIVE_STATE_MISSING,
                                                DRIVE_STATE_OUTDATED)]
    _, herrs = es._fanout([
        (lambda i=i: es.disks[i].write_metadata(bucket, object_, fi))
        if i in bad else None for i in range(n)])
    after = list(states)
    for i in bad:
        if herrs[i] is None:
            after[i] = DRIVE_STATE_OK
            result.healed += 1
    result.after = after
    return result


def heal_bucket(es, bucket: str) -> dict:
    """Recreate the bucket volume on drives that miss it."""
    results, errors = es._fanout(
        [lambda d=d: d.stat_vol(bucket) for d in es.disks])
    missing = [i for i, r in enumerate(results) if r is None]
    if len(missing) == len(es.disks):
        raise ObjectNotFound(bucket, "")
    _, herrs = es._fanout([
        (lambda i=i: es.disks[i].make_vol_if_missing(bucket))
        if i in missing else None for i in range(len(es.disks))])
    return {"bucket": bucket, "missing": len(missing),
            "healed": sum(1 for i, e in enumerate(herrs)
                          if i in missing and e is None)}


MRF_PATH = "mrf/pending.json"


class MRFQueue:
    """Most-recently-failed heal queue: partial writes retry immediately
    in the background (reference: cmd/mrf.go, bounded queue + worker).

    Pending entries persist to the system volume (best-effort, across
    all drives) whenever the queue has been dirty for a moment, and are
    reloaded+replayed at boot — the reference saves its MRF queue on
    shutdown and re-queues it at startup (cmd/mrf.go:155 healMRFDir)."""

    _PERSIST_EVERY = 2.0

    def __init__(self, es, max_items: int = 100_000, retries: int = 3,
                 persist: bool = True):
        self.es = es
        self.q: "queue.Queue[tuple]" = queue.Queue(maxsize=max_items)
        self.retries = retries
        self.healed = 0
        # Two failure counters with very different severities:
        # `spilled` — bounded-queue overflow that parked the entry in
        # the persisted pending set (nothing lost, replays later);
        # `dropped` — retries exhausted, the heal is genuinely gone.
        # Exported separately so alerting on real loss is possible.
        self.spilled = 0
        self.dropped = 0
        self._persist = persist
        # (bucket, obj, vid) -> queued? False = overflow spill: the
        # entry could not enter the bounded queue but stays pending, so
        # it persists across save/boot cycles and re-feeds when the
        # queue drains — queue.Full must never silently lose a heal.
        self._pending: dict[tuple, bool] = {}
        self._dirty = False
        self._last_save = 0.0
        self._mu = threading.Lock()
        self._stop = threading.Event()
        if persist:
            self._load()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def enqueue(self, bucket: str, object_: str, version_id: str = "") -> None:
        key = (bucket, object_, version_id)
        with self._mu:
            self._pending[key] = True
            self._dirty = True
        try:
            self.q.put_nowait((bucket, object_, version_id, 0))
        except queue.Full:
            # Spill: stays in _pending (persisted, replayed when the
            # queue drains or at the next boot).
            self.spilled += 1
            with self._mu:
                self._pending[key] = False

    # -- persistence ----------------------------------------------------

    def _load(self) -> None:
        import json
        from minio_tpu.storage.local import SYS_VOL
        # Union across drives: a pending heal recorded by ANY healthy
        # drive replays (a flaky drive with a stale copy must not make
        # entries vanish — losing a heal is worse than re-running one,
        # and heals are idempotent).
        entries: dict[tuple, int] = {}
        for d in self.es.disks:
            try:
                items = json.loads(d.read_all(SYS_VOL, MRF_PATH))
            except Exception:  # noqa: BLE001 - absent / offline
                continue
            for it in items:
                try:
                    entries[(it["b"], it["o"], it.get("v", ""))] = 1
                except TypeError:
                    continue
        for (b, o, v) in entries:
            self._pending[(b, o, v)] = True
            try:
                self.q.put_nowait((b, o, v, 0))
            except queue.Full:
                self.spilled += 1
                self._pending[(b, o, v)] = False   # re-fed as q drains

    def _save(self) -> None:
        import json
        from minio_tpu.storage.local import SYS_VOL
        with self._mu:
            items = [{"b": b, "o": o, "v": v}
                     for (b, o, v) in self._pending]
            self._dirty = False
        blob = json.dumps(items).encode()

        def write(d):
            def go():
                try:
                    d.write_all(SYS_VOL, MRF_PATH, blob)
                except Exception:  # noqa: BLE001 - best effort
                    pass
            return go
        self.es._fanout([write(d) for d in self.es.disks])

    def _maybe_persist(self) -> None:
        if not self._persist:
            return
        now = time.time()
        if self._dirty and now - self._last_save >= self._PERSIST_EVERY:
            self._last_save = now
            self._save()

    def save_now(self) -> None:
        """Flush pending entries to disk (shutdown / testing hook)."""
        if self._persist:
            self._save()

    # -- worker ---------------------------------------------------------

    def _refill_one(self) -> None:
        """Promote one overflow-spilled pending entry into the bounded
        queue now that it has room."""
        with self._mu:
            key = next((k for k, queued in self._pending.items()
                        if not queued), None)
            if key is None:
                return
            self._pending[key] = True
        try:
            self.q.put_nowait((*key, 0))
        except queue.Full:
            with self._mu:
                if key in self._pending:
                    self._pending[key] = False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._maybe_persist()
            except Exception:  # noqa: BLE001 - e.g. pool torn down at exit
                pass
            try:
                bucket, object_, vid, attempt = self.q.get(timeout=0.2)
            except queue.Empty:
                self._refill_one()
                continue
            try:
                # MRF entries come from observed failures (degraded reads,
                # bitrot hits, partial writes), so verify deeply.
                heal_object(self.es, bucket, object_, vid, deep=True)
                self.healed += 1
                with self._mu:
                    self._pending.pop((bucket, object_, vid), None)
                    self._dirty = True
            except Exception:  # noqa: BLE001 - retry w/ backoff, then drop
                if attempt + 1 < self.retries and not self._stop.is_set():
                    time.sleep(min(2 ** attempt * 0.05, 1.0))
                    try:
                        self.q.put_nowait((bucket, object_, vid, attempt + 1))
                    except queue.Full:
                        # Spill back to pending: retried on a later
                        # boot/save cycle rather than silently lost.
                        self.spilled += 1
                        with self._mu:
                            if (bucket, object_, vid) in self._pending:
                                self._pending[(bucket, object_, vid)] = False
                else:
                    self.dropped += 1
                    with self._mu:
                        self._pending.pop((bucket, object_, vid), None)
                        self._dirty = True
            finally:
                self.q.task_done()

    def stats(self) -> dict:
        with self._mu:
            return {"healed": self.healed, "spilled": self.spilled,
                    "dropped": self.dropped,
                    "pending": len(self._pending)}

    def drain(self, timeout: float = 10.0) -> None:
        """Testing hook: wait until queued AND in-flight items finish."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.q.unfinished_tasks == 0:
                return
            time.sleep(0.02)

    def stop(self) -> None:
        self._stop.set()
        self._worker.join(timeout=2)
        if self._persist:
            try:
                self._save()
            except Exception:  # noqa: BLE001 - shutdown best effort
                pass
