"""Object-layer types and errors (ObjectLayer interface vocabulary).

Mirrors the reference's object-API types (cmd/object-api-datatypes.go,
cmd/object-api-errors.go) at the granularity the S3 front-end needs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


class ObjectError(Exception):
    """Base class; carries bucket/object for S3 error rendering."""

    def __init__(self, bucket: str = "", object_: str = "", msg: str = ""):
        self.bucket = bucket
        self.object = object_
        super().__init__(msg or f"{type(self).__name__}: {bucket}/{object_}")


class BucketNotFound(ObjectError):
    pass


class BucketExists(ObjectError):
    pass


class BucketNotEmpty(ObjectError):
    pass


class ObjectNotFound(ObjectError):
    pass


class VersionNotFound(ObjectError):
    pass


class MethodNotAllowed(ObjectError):
    """e.g. GET on a delete marker."""


class InvalidRange(ObjectError):
    pass


class ReadQuorumError(ObjectError):
    """errErasureReadQuorum: not enough consistent metadata/shards."""


class WriteQuorumError(ObjectError):
    """errErasureWriteQuorum: too few successful writes."""


class InvalidArgument(ObjectError):
    pass


class PreconditionFailed(ObjectError):
    pass


@dataclasses.dataclass
class BucketInfo:
    name: str
    created: int = 0  # ns epoch
    versioning: bool = False


@dataclasses.dataclass
class ObjectInfo:
    bucket: str = ""
    name: str = ""
    mod_time: int = 0
    size: int = 0
    etag: str = ""
    content_type: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    user_metadata: dict = dataclasses.field(default_factory=dict)
    parts: list = dataclasses.field(default_factory=list)
    is_dir: bool = False
    actual_size: int = 0
    storage_class: str = "STANDARD"
    user_tags: str = ""         # URL-encoded object tags
    # Internal metadata (SSE params and friends), filtered out of the
    # user-facing x-amz-meta-* surface.
    internal_metadata: dict = dataclasses.field(default_factory=dict)
    # Resolved byte range of the payload returned by get_object.
    range_start: int = 0
    range_length: int = 0


@dataclasses.dataclass
class ListObjectsInfo:
    is_truncated: bool = False
    next_marker: str = ""
    next_continuation_token: str = ""
    objects: list[ObjectInfo] = dataclasses.field(default_factory=list)
    prefixes: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PutOptions:
    version_id: str = ""
    versioned: bool = False
    user_metadata: dict = dataclasses.field(default_factory=dict)
    content_type: str = ""
    storage_class: str = "STANDARD"
    mod_time: int = 0
    tags: str = ""              # URL-encoded object tags (x-amz-tagging)
    # Internal (never user-visible) metadata, e.g. SSE crypto params;
    # keys must start with "x-internal-".
    internal_metadata: dict = dataclasses.field(default_factory=dict)
    # Pre-computed etag override (content transforms hash the LOGICAL
    # bytes; the store would otherwise hash what it stores).
    etag: str = ""
    # Fused single-pass data plane plan (object/transform.TransformSpec,
    # duck-typed to avoid an import cycle): when set, the erasure layer
    # runs digest/compress/DARE/frame as ONE native pass over the body
    # instead of the caller pre-transforming the payload.
    transform: Optional[object] = None


@dataclasses.dataclass
class GetOptions:
    version_id: str = ""
    offset: int = 0
    length: int = -1   # -1 = to end
    # Parsed HTTP Range header (start|None, end|None); resolved against
    # the object size inside get_object so range requests cost a single
    # metadata fan-out. Overrides offset/length when set.
    range_spec: Optional[tuple] = None


@dataclasses.dataclass
class DeleteOptions:
    version_id: str = ""
    versioned: bool = False
    # Versioning-SUSPENDED simple delete: write a delete marker with the
    # null versionId, REPLACING any existing null version/marker —
    # AWS's suspended-bucket semantics (reference:
    # internal/bucket/versioning/versioning.go:36,76 treats Suspended
    # as a distinct state, not versioning-off).
    null_marker: bool = False
    # Internal metadata stamped onto a delete marker AT creation (e.g.
    # the replication PENDING status): markers must carry their status
    # from the first quorum write, or a crash between delete and stamp
    # leaves a marker the scanner can never resync.
    marker_metadata: Optional[dict] = None
    # Version id to mint the delete marker with instead of a fresh
    # uuid: replicated deletes carry the SOURCE marker's id so the two
    # clusters' markers are the same version (and re-delivery replaces
    # rather than stacks).  Ignored for null markers.
    marker_version_id: str = ""


@dataclasses.dataclass
class DeletedObject:
    object_name: str = ""
    version_id: str = ""
    delete_marker: bool = False
    delete_marker_version_id: str = ""
