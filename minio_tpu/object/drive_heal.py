"""Drive lifecycle: hot replacement with a checkpointed bulk heal.

The analogue of the reference's new-disk healing stack
(cmd/background-newdisks-heal-ops.go): a drive that dies and is swapped
for a fresh one at RUNTIME is detected while serving, re-formatted with
its slot's identity (scanner.check_drive_formats, the analogue of
formatErasureFixV3), marked healing (storage/local.HEALING_FILE, the
analogue of .healing.bin), and repopulated by a throttled set-wide bulk
heal that walks every bucket/object through the standard heal_object
path.

Semantics while a drive is healing:
  * writes resume IMMEDIATELY — new data lands on the replaced drive
    the moment its format is restored, so the heal backlog only ever
    shrinks;
  * reads participate as reconstruct sources only in the natural
    sense: the drive was wiped, so it holds no stale data — objects it
    already carries (healed or newly written) serve normally, objects
    it misses return not-found and the erasure layer reconstructs from
    the other drives;
  * readiness (/minio/health/ready) reports the set degraded until the
    bulk heal finishes (s3/server._health_ready).

The bulk heal checkpoints its position (bucket, last completed object)
into the healing marker every few objects, so a process restart — or a
crash — resumes where it stopped instead of at 'a' (the reference
persists healingTracker the same way). It is worker-0-gated like the
scanner (n pre-forked workers bulk-healing the same drives would
multiply every heal by n) and sheds under admission pressure: when the
front end is queueing clients, background repair yields.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

from minio_tpu.storage.local import (clear_healing, read_healing,
                                     write_healing)

# Objects healed between checkpoint persists (reference:
# healingTracker.bucketsCompleted-style periodic saves).
CHECKPOINT_EVERY = 64


def new_tracker(set_index: int, disk_index: int,
                endpoint: str = "") -> dict:
    """A fresh healing tracker for a just-adopted replacement drive."""
    return {
        "started": time.time(),
        "set_index": set_index,
        "disk_index": disk_index,
        "endpoint": endpoint,
        "objects_scanned": 0,
        "objects_healed": 0,
        "objects_failed": 0,
        "bytes_healed": 0,
        "checkpoint_bucket": "",
        "checkpoint_object": "",
        "finished": False,
    }


def mark_healing(disk, set_index: int, disk_index: int,
                 endpoint: str = "") -> bool:
    """Write a fresh healing marker unless the drive already carries a
    live checkpoint (never clobber resume state). The indices are
    advisory/display — the manager re-stamps them from its own set
    list when it adopts the tracker. Returns True when written."""
    if read_healing(disk) is not None:
        return False
    write_healing(disk, new_tracker(set_index, disk_index, endpoint))
    return True


def admission_pressure(admission) -> bool:
    """True when the front end is visibly queueing or saturated — the
    bulk heal's yield signal. Reads the AdmissionController snapshot
    (s3/admission.py); absent/odd controllers mean no pressure."""
    if admission is None:
        return False
    try:
        snap = admission.snapshot()
    except Exception:  # noqa: BLE001 - controller mid-teardown
        return False
    for v in snap.values():
        if not isinstance(v, dict):
            continue
        if v.get("waiting", 0) > 0:
            return True
        limit = v.get("limit", 0)
        if limit and v.get("in_flight", 0) >= limit:
            return True
    return False


def bulk_heal_drive(es, disk_idx: int, tracker: dict,
                    stop: Optional[threading.Event] = None,
                    throttle: float = 0.0,
                    pressure: Optional[Callable[[], bool]] = None,
                    checkpoint_every: int = CHECKPOINT_EVERY) -> dict:
    """Set-wide bulk heal converging one replaced drive: every bucket
    volume, then every object (sorted, resumable), through heal_object
    (reference: cmd/global-heal.go healErasureSet driven by the
    new-disk flow). Mutates + persists `tracker` as it goes; returns it
    finished (or checkpointed, when `stop` fired mid-sweep).
    """
    from minio_tpu.object.healing import heal_bucket, heal_object
    from minio_tpu.object.scanner import _walk_all_drives
    from minio_tpu.storage.meta import XLMeta

    disk = es.disks[disk_idx]
    since_ckpt = 0

    def version_ids(copies) -> list:
        """EVERY version of the walked key, from any parseable journal
        copy — a replaced drive must get old versions and delete
        markers back too, not just the latest ("" falls back to
        latest-only when no copy parses)."""
        for _i, blob in copies:
            try:
                vids = [v.get("vid", "") for v in XLMeta.load(blob).versions]
                if vids:
                    return vids
            except Exception:  # noqa: BLE001 - corrupt copy: try next
                continue
        return [""]

    def save(bucket: str = "", obj: str = "") -> None:
        if bucket:
            tracker["checkpoint_bucket"] = bucket
            tracker["checkpoint_object"] = obj
        try:
            write_healing(disk, tracker)
        except Exception:  # noqa: BLE001 - drive hiccup: next checkpoint
            pass

    ckpt_bucket = tracker.get("checkpoint_bucket", "")
    ckpt_object = tracker.get("checkpoint_object", "")
    try:
        buckets = sorted(b.name for b in es.list_buckets())
    except Exception:  # noqa: BLE001 - set unreadable: retry next poll
        return tracker
    for bucket in buckets:
        if bucket < ckpt_bucket:
            continue
        try:
            heal_bucket(es, bucket)
        except Exception:  # noqa: BLE001 - bucket vanished mid-sweep
            continue
        forward = ckpt_object if bucket == ckpt_bucket else ""
        for path, copies in _walk_all_drives(es, bucket,
                                             forward_from=forward):
            if stop is not None and stop.is_set():
                save(bucket, path)
                return tracker
            while pressure is not None and pressure():
                # Shed: clients are queueing; background repair yields
                # until the front end drains (checkpoint stays warm).
                if stop is not None and stop.is_set():
                    save(bucket, path)
                    return tracker
                time.sleep(0.05)
            tracker["objects_scanned"] += 1
            key_healed = False
            for vid in version_ids(copies):
                try:
                    r = heal_object(es, bucket, path, vid)
                    if r.healed and disk_idx < len(r.after) \
                            and r.before[disk_idx] != r.after[disk_idx]:
                        key_healed = True
                        tracker["bytes_healed"] += r.size
                except Exception:  # noqa: BLE001 - scanner/MRF retries
                    tracker["objects_failed"] += 1
                    break
            if key_healed:
                tracker["objects_healed"] += 1
            since_ckpt += 1
            if since_ckpt >= checkpoint_every:
                since_ckpt = 0
                save(bucket, path)
            if throttle:
                time.sleep(throttle)
        ckpt_object = ""
    tracker["finished"] = True
    tracker["finished_at"] = time.time()
    clear_healing(disk)
    return tracker


class DriveHealManager:
    """Per-process drive lifecycle manager.

    poll_once() is one detection pass: restore formats of fresh drives
    appearing in previously-formatted slots (while serving), then start
    — or resume, after a restart, from the persisted checkpoint — a
    bulk heal thread for every drive carrying an unfinished healing
    marker. start() runs poll_once on an interval (worker 0 only, wired
    by minio_tpu.server).
    """

    def __init__(self, sets: Sequence, set_size: int = 0,
                 throttle: float = 0.001,
                 checkpoint_every: int = CHECKPOINT_EVERY,
                 pressure: Optional[Callable[[], bool]] = None,
                 total_hint: Optional[Callable[[], int]] = None):
        self.sets = list(sets)
        self.set_size = set_size or (len(self.sets[0].disks)
                                     if self.sets else 0)
        self.throttle = throttle
        self.checkpoint_every = checkpoint_every
        self.pressure = pressure
        self.total_hint = total_hint      # e.g. scanner usage.objects
        self.formats_restored = 0
        self._mu = threading.Lock()
        # (set_idx, disk_idx) -> {"tracker": dict, "thread": Thread}
        self._active: dict[tuple, dict] = {}
        # Finished trackers kept for status/metrics continuity.
        self._done: dict[tuple, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- detection -------------------------------------------------------

    def poll_once(self) -> int:
        """One pass: format restore for fresh drives + bulk-heal
        start/resume for marked drives. Returns newly-started heals."""
        from minio_tpu.object.scanner import check_drive_formats
        try:
            self.formats_restored += check_drive_formats(self.sets,
                                                         self.set_size)
        except Exception:  # noqa: BLE001 - detection retries next poll
            pass
        started = 0
        for si, es in enumerate(self.sets):
            for di, d in enumerate(es.disks):
                tracker = read_healing(d)
                if tracker is None or tracker.get("finished"):
                    continue
                if self._ensure_heal(si, di, es, tracker):
                    started += 1
        return started

    def _ensure_heal(self, si: int, di: int, es, tracker: dict) -> bool:
        with self._mu:
            slot = self._active.get((si, di))
            if slot is not None and slot["thread"].is_alive():
                return False
            # Re-stamp identity from the manager's own topology: the
            # persisted indices are advisory (boot-time markers use the
            # pool-local row) and must not leak into live status keys.
            tracker["set_index"] = si
            tracker["disk_index"] = di
            tracker["endpoint"] = getattr(es.disks[di], "endpoint", "") \
                or tracker.get("endpoint", "")
            t = threading.Thread(
                target=self._run_heal, args=(si, di, es, tracker),
                daemon=True, name=f"drive-heal-{si}-{di}")
            self._active[(si, di)] = {"tracker": tracker, "thread": t}
        t.start()
        return True

    def _run_heal(self, si: int, di: int, es, tracker: dict) -> None:
        try:
            bulk_heal_drive(es, di, tracker, stop=self._stop,
                            throttle=self.throttle,
                            pressure=self.pressure,
                            checkpoint_every=self.checkpoint_every)
        except Exception:  # noqa: BLE001 - next poll resumes from ckpt
            pass
        if tracker.get("finished"):
            with self._mu:
                self._active.pop((si, di), None)
                self._done[(si, di)] = tracker

    # -- introspection ---------------------------------------------------

    def healing_drives(self) -> list[tuple]:
        with self._mu:
            return [k for k, v in self._active.items()
                    if v["thread"].is_alive()]

    def status(self) -> dict:
        """Admin-facing snapshot: one entry per healing (or recently
        finished) drive with progress counters and an ETA when a
        cluster object-count hint is available."""
        total = 0
        if self.total_hint is not None:
            try:
                # The hint (scanner usage) is CLUSTER-wide; a bulk heal
                # walks one set's share of the namespace, so scale it
                # down or the ETA never converges on multi-set layouts.
                total = int(self.total_hint()) // max(len(self.sets), 1)
            except Exception:  # noqa: BLE001 - hint optional
                total = 0
        drives = []
        with self._mu:
            live = [(k, dict(v["tracker"]), v["thread"].is_alive())
                    for k, v in self._active.items()]
            done = [(k, dict(t)) for k, t in self._done.items()]
        for (si, di), tracker, alive in live:
            entry = dict(tracker, set=si, drive=di,
                         state="healing" if alive else "paused")
            scanned = tracker.get("objects_scanned", 0)
            elapsed = max(time.time() - tracker.get("started", 0), 1e-6)
            rate = scanned / elapsed
            if total and rate > 0:
                entry["eta_seconds"] = round(
                    max(total - scanned, 0) / rate, 1)
            drives.append(entry)
        for (si, di), tracker in done:
            drives.append(dict(tracker, set=si, drive=di, state="done"))
        return {"formats_restored": self.formats_restored,
                "drives": drives}

    def wait(self, timeout: float = 30.0) -> bool:
        """Testing hook: block until every active bulk heal finishes."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._mu:
                threads = [v["thread"] for v in self._active.values()]
            if not any(t.is_alive() for t in threads):
                return True
            time.sleep(0.02)
        return False

    # -- lifecycle -------------------------------------------------------

    def start(self, interval: float = 10.0) -> None:
        if self._thread is not None:
            return

        def run():
            while not self._stop.wait(interval):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 - manager must survive
                    continue

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="drive-heal-manager")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        with self._mu:
            threads = [v["thread"] for v in self._active.values()]
        for t in threads:
            t.join(timeout=2)
