"""Bucket lifecycle (ILM): rule parsing and scanner-driven expiry.

The analogue of the reference's ILM stack (internal/bucket/lifecycle +
cmd/bucket-lifecycle.go): lifecycle XML persists in bucket metadata
(s3 PUT ?lifecycle), and the background scanner evaluates every scanned
object against its bucket's rules, applying expirations through the
normal delete paths. Supported v1 actions: Expiration (Days/Date),
NoncurrentVersionExpiration (NoncurrentDays), and
ExpiredObjectDeleteMarker cleanup.
"""

from __future__ import annotations

import dataclasses
import datetime
import time
import xml.etree.ElementTree as ET
from typing import Optional, Sequence

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
_NS = f"{{{XMLNS}}}"
_DAY = 86400.0


class LifecycleError(Exception):
    pass


@dataclasses.dataclass
class Rule:
    rule_id: str = ""
    enabled: bool = True
    prefix: str = ""
    expiration_days: int = 0
    expiration_date: float = 0.0          # epoch seconds
    expire_delete_marker: bool = False
    noncurrent_days: int = 0
    # Transition: move data to a named warm tier after an age or at a
    # date (reference: lifecycle.Transition, StorageClass = tier name).
    transition_days: int = 0
    transition_date: float = 0.0          # epoch seconds
    transition_tier: str = ""
    noncurrent_transition_days: int = 0
    noncurrent_transition_tier: str = ""


def _text(el, name: str) -> str:
    if el is None:
        return ""
    return el.findtext(f"{_NS}{name}") or el.findtext(name) or ""


def _find(el, name: str):
    if el is None:
        return None
    found = el.find(f"{_NS}{name}")
    return found if found is not None else el.find(name)


def parse_lifecycle(xml: bytes | str) -> list[Rule]:
    """LifecycleConfiguration XML -> rules (raises LifecycleError)."""
    try:
        root = ET.fromstring(xml)
    except ET.ParseError as e:
        raise LifecycleError(f"malformed lifecycle XML: {e}") from None
    rules: list[Rule] = []
    for rel in list(root.iter(f"{_NS}Rule")) + list(root.iter("Rule")):
        r = Rule()
        r.rule_id = _text(rel, "ID")
        r.enabled = _text(rel, "Status") != "Disabled"
        filt = _find(rel, "Filter")
        r.prefix = _text(filt, "Prefix") or _text(rel, "Prefix")
        exp = _find(rel, "Expiration")
        if exp is not None:
            days = _text(exp, "Days")
            if days:
                try:
                    r.expiration_days = int(days)
                except ValueError:
                    raise LifecycleError(f"bad Days {days!r}") from None
                if r.expiration_days <= 0:
                    raise LifecycleError("Expiration Days must be positive")
            date = _text(exp, "Date")
            if date:
                try:
                    dt = datetime.datetime.fromisoformat(
                        date.replace("Z", "+00:00"))
                    if dt.tzinfo is None:
                        dt = dt.replace(tzinfo=datetime.timezone.utc)
                    r.expiration_date = dt.timestamp()
                except ValueError:
                    raise LifecycleError(f"bad Date {date!r}") from None
            if _text(exp, "ExpiredObjectDeleteMarker") == "true":
                r.expire_delete_marker = True
        nce = _find(rel, "NoncurrentVersionExpiration")
        if nce is not None:
            nd = _text(nce, "NoncurrentDays")
            if nd:
                try:
                    r.noncurrent_days = int(nd)
                except ValueError:
                    raise LifecycleError(
                        f"bad NoncurrentDays {nd!r}") from None
        tr = _find(rel, "Transition")
        if tr is not None:
            tier = _text(tr, "StorageClass")
            days = _text(tr, "Days")
            date = _text(tr, "Date")
            if not tier:
                raise LifecycleError("Transition needs StorageClass")
            if not days and not date:
                # A bare StorageClass would otherwise default to
                # Days=0 and ship EVERYTHING on the next scan.
                raise LifecycleError("Transition needs Days or Date")
            if date:
                try:
                    dt = datetime.datetime.fromisoformat(
                        date.replace("Z", "+00:00"))
                    if dt.tzinfo is None:
                        dt = dt.replace(tzinfo=datetime.timezone.utc)
                    r.transition_date = dt.timestamp()
                except ValueError:
                    raise LifecycleError(
                        f"bad Transition Date {date!r}") from None
            try:
                r.transition_days = int(days or "0")
            except ValueError:
                raise LifecycleError(f"bad Transition Days {days!r}") \
                    from None
            if r.transition_days < 0:
                raise LifecycleError("Transition Days must be >= 0")
            r.transition_tier = tier
        ntr = _find(rel, "NoncurrentVersionTransition")
        if ntr is not None:
            tier = _text(ntr, "StorageClass")
            days = _text(ntr, "NoncurrentDays")
            if not tier:
                raise LifecycleError(
                    "NoncurrentVersionTransition needs StorageClass")
            try:
                r.noncurrent_transition_days = int(days or "0")
            except ValueError:
                raise LifecycleError(
                    f"bad NoncurrentDays {days!r}") from None
            if r.noncurrent_transition_days < 0:
                raise LifecycleError("NoncurrentDays must be >= 0")
            r.noncurrent_transition_tier = tier
        rules.append(r)
    if not rules:
        raise LifecycleError("lifecycle configuration has no rules")
    return rules


@dataclasses.dataclass
class Action:
    # "expire_latest" | "delete_version" | "drop_marker" | "transition"
    kind: str
    version_id: str = ""
    rule_id: str = ""
    tier: str = ""


def _tiered(v) -> bool:
    """Already transitioned? (metadata carries the tier pointer)."""
    from minio_tpu.object.tier import META_TIER
    return bool((getattr(v, "metadata", None) or {}).get(META_TIER))


def evaluate(rules: Sequence[Rule], key: str, versions,
             now: Optional[float] = None) -> list[Action]:
    """Decide expirations for one object's version stack (latest first,
    FileInfo-like entries with .mod_time ns / .deleted / .version_id).

    Mirrors the reference's lifecycle.Eval ordering: latest-version
    expiry, noncurrent-version expiry, lone-delete-marker cleanup."""
    if not versions:
        return []
    now = time.time() if now is None else now
    actions: list[Action] = []
    latest = versions[0]
    for r in rules:
        if not r.enabled or not key.startswith(r.prefix):
            continue
        latest_age = now - latest.mod_time / 1e9
        if not latest.deleted:
            expired = (r.expiration_days and
                       latest_age > r.expiration_days * _DAY) or \
                      (r.expiration_date and now >= r.expiration_date)
            if expired:
                actions.append(Action("expire_latest", rule_id=r.rule_id))
            elif r.transition_tier and not _tiered(latest):
                due = now >= r.transition_date if r.transition_date \
                    else latest_age > r.transition_days * _DAY
                if due:
                    actions.append(Action("transition",
                                          version_id=latest.version_id,
                                          rule_id=r.rule_id,
                                          tier=r.transition_tier))
        elif r.expire_delete_marker and len(versions) == 1:
            # Lone delete marker left behind after its versions expired.
            actions.append(Action("drop_marker",
                                  version_id=latest.version_id,
                                  rule_id=r.rule_id))
        if r.noncurrent_transition_tier:
            for newer, v in zip(versions, versions[1:]):
                if v.deleted or _tiered(v):
                    continue
                noncurrent_since = newer.mod_time / 1e9
                if now - noncurrent_since > \
                        r.noncurrent_transition_days * _DAY:
                    actions.append(Action(
                        "transition", version_id=v.version_id,
                        rule_id=r.rule_id,
                        tier=r.noncurrent_transition_tier))
        if r.noncurrent_days:
            # A version becomes noncurrent when the next-newer version
            # supersedes it; its age counts from that moment.
            for newer, v in zip(versions, versions[1:]):
                noncurrent_since = newer.mod_time / 1e9
                if now - noncurrent_since > r.noncurrent_days * _DAY \
                        and v.version_id:
                    actions.append(Action("delete_version",
                                          version_id=v.version_id,
                                          rule_id=r.rule_id))
    # Dedup (multiple rules can fire on the same target).
    seen = set()
    out = []
    for a in actions:
        k = (a.kind, a.version_id)
        if k not in seen:
            seen.add(k)
            out.append(a)
    return out


def make_scanner_hook(now_fn=None, on_delete=None):
    """Scanner on_object callback applying ILM to scanned objects.

    now_fn: clock override for accelerated tests.
    on_delete: callback `(es, bucket, key, DeletedObject)` fired after
    a successful expire_latest — the replication plane uses it to
    propagate ILM-created delete markers (the handler-side enqueue
    never sees scanner deletes)."""
    from minio_tpu.object.types import DeleteOptions

    cache: dict = {}

    def rules_for(es, bucket: str):
        doc = es.get_bucket_meta(bucket).get("config:lifecycle")
        if not doc:
            return None
        hit = cache.get(bucket)
        if hit is not None and hit[0] == doc:
            return hit[1]
        try:
            rules = parse_lifecycle(doc)
        except LifecycleError:
            rules = None
        cache[bucket] = (doc, rules)
        return rules

    def locked(versions, version_id: str) -> bool:
        """WORM guard on the scanner's own deletes: ILM must never
        destroy a version under active retention or legal hold
        (reference: lifecycle evaluation consults object-lock state,
        internal/bucket/lifecycle + enforceRetentionForDeletion).
        Retention uses the REAL clock even under now_fn acceleration —
        a test-accelerated ILM age must not unlock WORM data."""
        import time as _t
        from minio_tpu.object import objectlock as olock
        for v in versions:
            if v.version_id != version_id:
                continue
            m = getattr(v, "metadata", None) or {}
            if not (m.get(olock.META_MODE) or m.get(olock.META_HOLD)):
                return False
            return olock.check_version_deletable(
                m, _t.time_ns(), False) is not None
        return False

    def hook(es, bucket: str, key: str, versions) -> None:
        rules = rules_for(es, bucket)
        if not rules:
            return
        now = now_fn() if now_fn is not None else None
        versioned = bool(es.get_bucket_meta(bucket).get("versioning"))
        for a in evaluate(rules, key, versions, now=now):
            try:
                if a.kind == "expire_latest":
                    # Versioned: stacks a delete marker (never destroys
                    # data). Unversioned destroys the only copy — and an
                    # unversioned bucket cannot be lock-enabled, so no
                    # lock check is needed here.
                    deleted = es.delete_object(
                        bucket, key, DeleteOptions(versioned=versioned))
                    if on_delete is not None:
                        try:
                            on_delete(es, bucket, key, deleted)
                        except Exception:  # noqa: BLE001 - advisory
                            pass
                elif a.kind in ("delete_version", "drop_marker"):
                    if locked(versions, a.version_id):
                        continue
                    es.delete_object(bucket, key, DeleteOptions(
                        version_id=a.version_id, versioned=versioned))
                elif a.kind == "transition":
                    # WORM versions may still transition (the data
                    # remains readable; only its location changes) —
                    # the reference transitions locked objects too.
                    es.transition_version(bucket, key, a.version_id,
                                          a.tier)
            except Exception:  # noqa: BLE001 - next cycle retries
                continue
    return hook
