"""Warm tiers: remote targets for ILM transitions.

The analogue of the reference's tiering stack (cmd/warm-backend.go:39,
cmd/tier.go, cmd/warm-backend-s3.go / -minio.go): named warm backends
persist in a quorum-replicated config document; lifecycle Transition
rules move an object's DATA to its tier while the version's metadata
(etag, user metadata, SSE params) stays local with a pointer; reads
stream through the tier transparently; deleting the version removes
the tier copy.

Backends:
- "fs": a local directory (tests; single-node cold storage).
- "s3": any S3-compatible endpoint via the internal RemoteS3 client —
  pointing one minio_tpu cluster's cold tier at another is the
  reference's warm-backend-minio shape.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from minio_tpu.storage.local import SYS_VOL

TIERS_PATH = "config/tiers.json"

# Internal version-metadata keys marking a transitioned version
# (reference: xl.meta transition fields, cmd/xl-storage-format-v2.go).
META_TIER = "x-internal-tier-name"
META_TIER_KEY = "x-internal-tier-key"
META_TIER_SIZE = "x-internal-tier-size"   # stored size in the tier


class TierError(Exception):
    pass


class FSWarmBackend:
    """Directory-backed tier."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def put(self, key: str, data: bytes) -> None:
        full = os.path.join(self.path, key)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, full)

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> bytes:
        try:
            with open(os.path.join(self.path, key), "rb") as f:
                f.seek(offset)
                return f.read() if length < 0 else f.read(length)
        except FileNotFoundError:
            raise TierError(f"tier object {key!r} missing") from None

    def local_path(self, key: str) -> str:
        """Filesystem path of the stored tier copy — the sendfile
        source probe (erasure-resident data is bitrot-framed per
        shard; the FS tier file is the one place an object's stored
        bytes live contiguously). Remote backends have no such path
        (duck-typed absence)."""
        return os.path.join(self.path, key)

    def remove(self, key: str) -> None:
        try:
            os.remove(os.path.join(self.path, key))
        except FileNotFoundError:
            pass


class S3WarmBackend:
    """S3-compatible remote tier via the internal SigV4 client."""

    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 bucket: str, prefix: str = ""):
        from minio_tpu.s3.client import RemoteS3
        self.remote = RemoteS3(endpoint, access_key, secret_key)
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:
        self.remote.put_object(self.bucket, self._key(key), data)

    def get(self, key: str, offset: int = 0, length: int = -1) -> bytes:
        from minio_tpu.s3.client import S3ClientError
        headers = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        try:
            st, _, body = self.remote.request(
                "GET", f"/{self.bucket}/{self._key(key)}", headers=headers)
        except S3ClientError as e:
            raise TierError(f"tier read failed: {e}") from None
        if st not in (200, 206):
            raise TierError(f"tier read failed: HTTP {st}")
        return body

    def remove(self, key: str) -> None:
        try:
            self.remote.delete_object(self.bucket, self._key(key))
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass


def _build(cfg: dict):
    t = cfg.get("type", "")
    if t == "fs":
        return FSWarmBackend(cfg["path"])
    if t == "s3":
        return S3WarmBackend(cfg["endpoint"], cfg["accessKey"],
                             cfg["secretKey"], cfg["bucket"],
                             cfg.get("prefix", ""))
    raise TierError(f"unknown tier type {t!r}")


class TierRegistry:
    """Named tiers, quorum-persisted on the first pool's drives
    (reference: tier-config.bin via TierConfigMgr)."""

    _TTL = 5.0

    def __init__(self, sets):
        self._sets = list(sets)
        self._mu = threading.RLock()
        self._cfgs: dict[str, dict] = {}
        self._built: dict[str, object] = {}
        self._raw: bytes = b""
        self._loaded_at = 0.0
        self._load()

    def _disks(self):
        return [d for es in self._sets for d in es.disks]

    def _load(self) -> None:
        votes: dict[bytes, int] = {}
        for d in self._disks():
            try:
                blob = d.read_all(SYS_VOL, TIERS_PATH)
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        if votes:
            blob = max(votes.items(), key=lambda kv: kv[1])[0]
            if blob != self._raw:
                # Only an actual change invalidates the built-backend
                # cache — get() sits on every tiered GET, and churning
                # clients on unchanged config would cost every reader.
                try:
                    doc = json.loads(blob)
                    if isinstance(doc, dict):
                        self._cfgs = doc
                        self._built.clear()
                        self._raw = blob
                except ValueError:
                    pass
        self._loaded_at = time.monotonic()

    def _save(self) -> None:
        blob = json.dumps(self._cfgs, sort_keys=True).encode()
        ok = 0
        for d in self._disks():
            try:
                d.write_all(SYS_VOL, TIERS_PATH, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(self._disks()) // 2 + 1:
            raise TierError("could not persist tier config to a quorum")
        self._raw = blob

    def _refresh(self) -> None:
        if time.monotonic() - self._loaded_at > self._TTL:
            self._load()

    def add(self, name: str, cfg: dict) -> None:
        if not name or not name.isalnum():
            raise TierError("tier name must be alphanumeric")
        _build(cfg)                     # validate before storing
        with self._mu:
            self._cfgs[name] = dict(cfg)
            self._built.pop(name, None)
            self._save()

    def remove(self, name: str) -> None:
        with self._mu:
            if self._cfgs.pop(name, None) is None:
                raise TierError(f"no such tier {name!r}")
            self._built.pop(name, None)
            self._save()

    def list(self) -> dict:
        with self._mu:
            self._refresh()
            out = {}
            for name, cfg in self._cfgs.items():
                c = dict(cfg)
                c.pop("secretKey", None)   # never echo secrets
                out[name] = c
            return out

    def get(self, name: str):
        with self._mu:
            self._refresh()
            b = self._built.get(name)
            if b is None:
                cfg = self._cfgs.get(name)
                if cfg is None:
                    raise TierError(f"no such tier {name!r}")
                b = self._built[name] = _build(cfg)
            return b


def tier_object_key(deployment_id: str, bucket: str, key: str,
                    version_id: str) -> str:
    """Remote name for a transitioned version — unique per version so
    overwrites never collide in the tier."""
    vid = version_id or "null"
    return f"{deployment_id}/{bucket}/{key}/{vid}"
