"""Plaintext-space read/write transforms over the object layer.

The reference routes every front end (S3 handlers, FTP/SFTP servers,
Select, replication) through one object-API layer that applies the
stored-representation transforms — SSE decryption (cmd/encryption-v1.go)
and transparent decompression (cmd/object-api-utils.go) — so a gateway
can never leak DARE ciphertext or compressed bytes to a client. This
module is that seam here: the S3 server's GET path and the FTP gateway
both resolve logical bytes through these functions.

All functions raise the crypto-layer errors (`sse.SSEError`,
`compress.CompressionError`); callers translate to their protocol's
error surface (S3Error / FTP 550).
"""

from __future__ import annotations

from minio_tpu.object.types import GetOptions


def resolve_range(spec, size: int):
    """Parsed Range spec -> (start, length) against a logical size."""
    from minio_tpu.object.erasure_object import _resolve_range
    return _resolve_range(spec, size, "", "")


def sse_check_head(h: dict, info) -> None:
    """HEAD/GET of an SSE-C object requires the matching key."""
    from minio_tpu.crypto import sse as sse_mod
    alg = info.internal_metadata.get(sse_mod.META_ALG, "")
    if alg != sse_mod.ALG_SSE_C:
        return
    customer = sse_mod.parse_sse_c(h)
    if customer is None:
        raise sse_mod.SSEError("InvalidRequest",
                               "object is SSE-C encrypted; key headers "
                               "required")
    if customer[1] != info.internal_metadata.get(sse_mod.META_KEY_MD5):
        raise sse_mod.SSEError("AccessDenied", "wrong SSE-C key")


def get_compressed(ol, bucket, key, vid, spec, info):
    """Ranged read of a compressed object: fetch the covering stored
    blocks, decompress, trim to the plaintext range. Returns
    (info, chunks, start, length)."""
    from minio_tpu.crypto import compress as comp
    start, length = (resolve_range(spec, info.size)
                     if spec else (0, info.size))
    info.range_start, info.range_length = start, length
    if length <= 0 or info.size == 0:
        return info, (b for b in ()), start, max(length, 0)
    imeta = info.internal_metadata
    lo, ln = comp.stored_range(imeta, start, length)
    pin = vid or info.version_id
    _, stored = ol.get_object(
        bucket, key, GetOptions(version_id=pin, offset=lo, length=ln))
    plain = comp.decompress_range(stored, imeta, start, length,
                                  stored_base=lo)
    # Generator (not iter([...])): GET handlers' finally call
    # chunks.close().
    return info, (c for c in (plain,)), start, length


def get_encrypted(ol, kms, bucket, key, vid, spec, h, info):
    """Ranged decrypting GET: map the plaintext range onto
    package-aligned ciphertext, stream, decrypt, trim. An SSE multipart
    object is a sequence of independent per-part DARE streams
    (reference: cmd/encryption-v1.go:643 part-boundary decryption); a
    single PUT is one stream. Returns (info, chunks, start, length)."""
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.crypto.dare import (PACKAGE_SIZE, decrypt_packages,
                                       encrypt_stream_size, package_range)
    customer = sse_mod.parse_sse_c(h)
    data_key, nonce = sse_mod.decrypt_params(
        bucket, key, info.internal_metadata, kms, customer)
    start, length = (resolve_range(spec, info.size)
                     if spec else (0, info.size))
    info.range_start, info.range_length = start, length
    if length <= 0 or info.size == 0:
        return info, (b for b in ()), start, max(length, 0)
    if info.internal_metadata.get(sse_mod.META_MULTIPART) and info.parts:
        gen = decrypt_parts_gen(ol, bucket, key, vid or info.version_id,
                                info, data_key, nonce, start, length)
        return info, gen, start, length
    first, c_off, c_len = package_range(start, length)
    c_size = encrypt_stream_size(info.size)
    c_len = min(c_len, c_size - c_off)
    _, raw = ol.get_object_stream(
        bucket, key, GetOptions(version_id=vid, offset=c_off,
                                length=c_len))
    chunks = decrypt_packages(raw, data_key, nonce, first,
                              start - first * PACKAGE_SIZE, length)
    return info, chunks, start, length


def decrypt_parts_gen(ol, bucket, key, vid, info, data_key, nonce,
                      start, length):
    """Plaintext range [start, start+length) across per-part DARE
    streams. Part boundaries in the STORED stream are the summed
    ciphertext part sizes; in the plaintext space the summed logical
    sizes. The whole covering stored range is fetched in ONE
    get_object_stream call — the per-part slices are contiguous (first
    part reads to its stored end, middles whole, last from its start),
    and a single read means a single version resolution, so a concurrent
    overwrite in an unversioned bucket cannot interleave versions
    mid-response. Each part decrypts under its derived key and its own
    stored base nonce."""
    import base64 as _b64

    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.crypto.dare import (PACKAGE_SIZE, decrypt_packages,
                                       package_range)
    # Plan: (part, first_seq, skip, plain_len, stored_lo, stored_len)
    plan = []
    pos, remaining = start, length
    plain_off = stored_off = 0
    for p in info.parts:
        if remaining <= 0:
            break
        if pos >= plain_off + p.actual_size:
            plain_off += p.actual_size
            stored_off += p.size
            continue
        in_off = pos - plain_off
        in_len = min(remaining, p.actual_size - in_off)
        first, c_off, c_len = package_range(in_off, in_len)
        c_len = min(c_len, p.size - c_off)
        plan.append((p, first, in_off - first * PACKAGE_SIZE,
                     in_len, stored_off + c_off, c_len))
        pos += in_len
        remaining -= in_len
        plain_off += p.actual_size
        stored_off += p.size
    if not plan:
        return
    lo = plan[0][4]
    hi = plan[-1][4] + plan[-1][5]
    _, raw = ol.get_object_stream(
        bucket, key, GetOptions(version_id=vid, offset=lo,
                                length=hi - lo))
    carry = bytearray()
    raw_iter = iter(raw)

    def take(n):
        """Yield exactly n bytes from the shared stored stream."""
        nonlocal carry
        while n > 0:
            if carry:
                chunk = bytes(carry[:n])
                del carry[:len(chunk)]
            else:
                try:
                    chunk = next(raw_iter)
                except StopIteration:
                    return       # decryptor reports the shortfall
                if len(chunk) > n:
                    carry.extend(chunk[n:])
                    chunk = chunk[:n]
            n -= len(chunk)
            yield chunk

    try:
        for p, first, skip, plain_len, _s_lo, s_len in plan:
            part_nonce = _b64.b64decode(p.nonce) if p.nonce else nonce
            yield from decrypt_packages(
                take(s_len), sse_mod.part_key(data_key, p.number),
                part_nonce, first, skip, plain_len)
    finally:
        close = getattr(raw, "close", None)
        if close is not None:
            close()


def plaintext_stream(ol, kms, bucket, key, vid="", h=None):
    """(info, chunks) for the object's LOGICAL bytes, whatever its
    stored representation — the one entry point for gateways that have
    no transform headers of their own (FTP, SFTP). SSE-C objects raise
    SSEError (the server holds no key for them).

    The transform re-open is pinned to the version the first open
    resolved; in UNVERSIONED buckets there is no version to pin, so a
    concurrent overwrite between the two reads can tear — the same
    small window the S3 GET path (and the reference) accepts there."""
    h = h or {}
    info, chunks = ol.get_object_stream(bucket, key,
                                        GetOptions(version_id=vid))
    imeta = info.internal_metadata
    if imeta.get("x-internal-sse-alg"):
        chunks.close()
        sse_check_head(h, info)
        info, chunks, _, _ = get_encrypted(
            ol, kms, bucket, key, vid or info.version_id, None, h, info)
    elif imeta.get("x-internal-comp"):
        chunks.close()
        info, chunks, _, _ = get_compressed(
            ol, bucket, key, vid or info.version_id, None, info)
    return info, chunks


def sse_payload(ol, kms, bucket, key, payload, opts, h=None):
    """Wrap a put payload in DARE encryption when the request headers
    (SSE-C / SSE-S3) or the bucket's default-encryption config ask for
    it — the single put-side SSE seam for every writer (reference:
    cmd/bucket-encryption.go consulted by the object API layer, not
    just the S3 handler). Returns (payload, response headers)."""
    from minio_tpu.crypto import EncryptingPayload, encrypt_stream_size
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.utils.streams import Payload
    h = h or {}
    customer = sse_mod.parse_sse_c(h)
    if customer is None:
        enc_cfg = ol.get_bucket_meta(bucket).get("config:encryption")
        if not sse_mod.wants_sse_s3(h, enc_cfg):
            return payload, {}
    payload = Payload.wrap(payload)
    data_key, nonce, imeta = sse_mod.encrypt_metadata(
        bucket, key, payload.size, kms, customer)
    opts.internal_metadata.update(imeta)
    enc = EncryptingPayload(payload, data_key, nonce)
    out = Payload(enc, encrypt_stream_size(payload.size))
    if customer is not None:
        return out, {sse_mod.H_C_ALG: "AES256",
                     sse_mod.H_C_MD5: customer[1]}
    return out, {sse_mod.H_SSE: "AES256"}
