"""Plaintext-space read/write transforms over the object layer.

The reference routes every front end (S3 handlers, FTP/SFTP servers,
Select, replication) through one object-API layer that applies the
stored-representation transforms — SSE decryption (cmd/encryption-v1.go)
and transparent decompression (cmd/object-api-utils.go) — so a gateway
can never leak DARE ciphertext or compressed bytes to a client. This
module is that seam here: the S3 server's GET path and the FTP gateway
both resolve logical bytes through these functions.

All functions raise the crypto-layer errors (`sse.SSEError`,
`compress.CompressionError`); callers translate to their protocol's
error surface (S3Error / FTP 550).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from minio_tpu.object.types import GetOptions
from minio_tpu.utils.latency import Histogram

# ---------------------------------------------------------------------------
# Fused single-pass data plane (ROADMAP "single-pass device data
# plane"): one GIL-free native call per buffered PUT computes the etag
# md5 + declared checksums, deflates into the block scheme, seals into
# DARE packages, and frames the stored stream's full erasure blocks
# (native/native.cc mtpu_transform_frame) — instead of one Python walk
# of the body per stage. The S3 handler plans the stages into a
# TransformSpec; the erasure layer executes it next to the framer
# (erasure_object._transform_frame_windows) where the EC geometry and
# the pooled staging buffers live. MTPU_TRANSFORM_FUSED=off is the
# kill-switch back to the layered pipeline (byte-identical output).
# ---------------------------------------------------------------------------

STAGES = ("digest", "compress", "encrypt", "frame")

_stat_mu = threading.Lock()
_put_requests = {"fused": 0, "legacy": 0}
_get_requests = {"fused": 0, "legacy": 0}
_bytes = {"put": 0, "get": 0}
_stage_hists = {s: Histogram() for s in STAGES}


def fused_put_enabled() -> bool:
    """The fused PUT plane runs when the native library carries the
    transform kernel and MTPU_TRANSFORM_FUSED is not "off"
    (native.feature is the one shared gate)."""
    from minio_tpu import native
    return native.feature("mtpu_transform_frame") is not None


def note_put(path: str, nbytes: int = 0, stage_ns=None) -> None:
    with _stat_mu:
        _put_requests[path] = _put_requests.get(path, 0) + 1
        _bytes["put"] += nbytes
        if stage_ns:
            for stage, ns in zip(STAGES, stage_ns):
                if ns:
                    _stage_hists[stage].observe(ns / 1e9)


def note_get(path: str, nbytes: int = 0) -> None:
    with _stat_mu:
        _get_requests[path] = _get_requests.get(path, 0) + 1
        _bytes["get"] += nbytes


def stats() -> dict:
    """Fused/legacy path split + byte counters + per-stage service
    histograms (s3/metrics.py renders minio_tpu_transform_*)."""
    with _stat_mu:
        return {
            "put_requests": dict(_put_requests),
            "get_requests": dict(_get_requests),
            "bytes": dict(_bytes),
            "stage_hists": {s: h.state() for s, h in _stage_hists.items()},
            "fused_enabled": fused_put_enabled(),
        }


def reset_stats() -> None:
    """Test/bench hook: zero the path-split counters."""
    with _stat_mu:
        for d in (_put_requests, _get_requests):
            for key in list(d):
                d[key] = 0
        for key in _bytes:
            _bytes[key] = 0


@dataclasses.dataclass
class TransformSpec:
    """The fused data-plane plan for ONE buffered PUT: which digest,
    compression, and encryption stages the single native pass runs,
    and (after the pass) what it produced. Built by the S3 handler
    (s3/server.py _put_object), executed by the erasure layer."""

    # Declared/trailer checksum algos beyond the etag md5 (any of
    # "sha256", "sha1", "crc32").
    algos: tuple = ()
    compress: bool = False
    enc_key: bytes = b""          # 32-byte DARE data key; b"" = no SSE
    enc_nonce: bytes = b""        # 12-byte DARE base nonce
    # Pre-commit verification hook (declared-checksum comparison): runs
    # right after the fused pass, BEFORE any disk write; raising aborts
    # the PUT with nothing committed — the layered path's
    # Payload-finish-hook timing, preserved.
    verify: Optional[Callable[["TransformSpec"], None]] = None
    # -- results (filled by the fused pass) --
    digests: dict = dataclasses.field(default_factory=dict)  # algo -> raw
    etag: str = ""
    plain_size: int = -1
    stored_size: int = -1
    comp_used: bool = False
    comp_ends: list = dataclasses.field(default_factory=list)
    # Internal-metadata updates the pass produced (compression index,
    # corrected DARE-stream size for compressed+encrypted objects).
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def encrypt(self) -> bool:
        return bool(self.enc_key)

    def run_verify(self) -> None:
        if self.verify is not None:
            self.verify(self)


def resolve_range(spec, size: int):
    """Parsed Range spec -> (start, length) against a logical size."""
    from minio_tpu.object.erasure_object import _resolve_range
    return _resolve_range(spec, size, "", "")


def sse_check_head(h: dict, info) -> None:
    """HEAD/GET of an SSE-C object requires the matching key."""
    from minio_tpu.crypto import sse as sse_mod
    alg = info.internal_metadata.get(sse_mod.META_ALG, "")
    if alg != sse_mod.ALG_SSE_C:
        return
    customer = sse_mod.parse_sse_c(h)
    if customer is None:
        raise sse_mod.SSEError("InvalidRequest",
                               "object is SSE-C encrypted; key headers "
                               "required")
    if customer[1] != info.internal_metadata.get(sse_mod.META_KEY_MD5):
        raise sse_mod.SSEError("AccessDenied", "wrong SSE-C key")


def _inflate_stream(raw, ends, first_block, stored_base, skip, length):
    """Windowed decompression: consume the STORED byte stream `raw`
    (starting at absolute stored offset `stored_base` = the start of
    `first_block`), inflate each run of whole compressed blocks the
    moment the window covers it — one GIL-free native call per run
    (crypto/compress.inflate_blocks) with the per-block Python loop as
    fallback — and yield plaintext, dropping `skip` leading bytes and
    stopping after `length`. Replaces the whole-blob
    decompress_range hop: memory stays O(window), never O(range)."""
    import zlib as _zl

    from minio_tpu.crypto import compress as comp
    produced = 0
    b = first_block
    base = stored_base
    buf = bytearray()
    try:
        for chunk in raw:
            buf += chunk
            nb = 0
            while b + nb < len(ends) and ends[b + nb] - base <= len(buf):
                nb += 1
            if not nb:
                continue
            window = bytes(buf[: ends[b + nb - 1] - base])
            plain = comp.inflate_blocks(window, ends, b, nb, base)
            if plain is None:
                parts = []
                for i in range(b, b + nb):
                    lo = (ends[i - 1] if i else 0) - base
                    try:
                        parts.append(_zl.decompress(window[lo:ends[i] -
                                                           base]))
                    except _zl.error:
                        raise comp.CompressionError(
                            f"block {i} fails decompression") from None
                plain = b"".join(parts)
            del buf[: len(window)]
            base += len(window)
            b += nb
            if skip:
                drop = min(skip, len(plain))
                plain = plain[drop:]
                skip -= drop
            take = min(len(plain), length - produced)
            if take:
                produced += take
                yield plain[:take]
            if produced >= length:
                return
        if produced < length:
            raise comp.CompressionError(
                "stored stream ended before the requested range")
    finally:
        close = getattr(raw, "close", None)
        if close is not None:
            close()


def get_compressed(ol, bucket, key, vid, spec, info):
    """Ranged read of a compressed object: STREAM the covering stored
    blocks and decompress window by window out of the pooled GET
    readahead (no whole-blob materialization). Returns
    (info, chunks, start, length)."""
    from minio_tpu.crypto import compress as comp
    start, length = (resolve_range(spec, info.size)
                     if spec else (0, info.size))
    info.range_start, info.range_length = start, length
    if length <= 0 or info.size == 0:
        return info, (b for b in ()), start, max(length, 0)
    imeta = info.internal_metadata
    lo, ln = comp.stored_range(imeta, start, length)
    ends = comp._index(imeta)
    pin = vid or info.version_id
    _, raw = ol.get_object_stream(
        bucket, key, GetOptions(version_id=pin, offset=lo, length=ln))
    first = start // comp.BLOCK
    note_get("fused" if comp._native_lib() is not None else "legacy",
             length)
    gen = _inflate_stream(raw, ends, first, lo,
                          start - first * comp.BLOCK, length)
    return info, gen, start, length


def get_encrypted(ol, kms, bucket, key, vid, spec, h, info):
    """Ranged decrypting GET: map the plaintext range onto
    package-aligned ciphertext, stream, decrypt, trim — window by
    window out of the pooled GET readahead (crypto/dare.py opens whole
    windows in one native call when the kernel library is present). An
    SSE multipart object is a sequence of independent per-part DARE
    streams (reference: cmd/encryption-v1.go:643 part-boundary
    decryption); a single PUT is one stream. A compressed+encrypted
    object layers verify -> decrypt -> decompress over the same
    windows: the plaintext range maps to compressed blocks, the block
    range to DARE packages, and both transforms run per window.
    Returns (info, chunks, start, length)."""
    from minio_tpu.crypto import compress as comp
    from minio_tpu.crypto import dare as dare_mod
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.crypto.dare import (PACKAGE_SIZE, decrypt_packages,
                                       encrypt_stream_size, package_range)
    customer = sse_mod.parse_sse_c(h)
    data_key, nonce = sse_mod.decrypt_params(
        bucket, key, info.internal_metadata, kms, customer)
    start, length = (resolve_range(spec, info.size)
                     if spec else (0, info.size))
    info.range_start, info.range_length = start, length
    if length <= 0 or info.size == 0:
        return info, (b for b in ()), start, max(length, 0)
    imeta = info.internal_metadata
    fused = dare_mod._native_lib() is not None
    if imeta.get(comp.META_SCHEME):
        # Compressed-then-encrypted single stream: plaintext range ->
        # covering compressed blocks -> covering DARE packages.
        ends = comp._index(imeta)
        first_block = start // comp.BLOCK
        c_lo, c_ln = comp.stored_range(imeta, start, length)
        dare_plain = int(imeta.get(sse_mod.META_SIZE, "0"))
        first, p_off, p_len = package_range(c_lo, c_ln)
        p_len = min(p_len, encrypt_stream_size(dare_plain) - p_off)
        _, raw = ol.get_object_stream(
            bucket, key, GetOptions(version_id=vid, offset=p_off,
                                    length=p_len))
        comp_stream = decrypt_packages(
            raw, data_key, nonce, first,
            c_lo - first * PACKAGE_SIZE, c_ln)
        note_get("fused" if fused else "legacy", length)
        gen = _inflate_stream(comp_stream, ends, first_block, c_lo,
                              start - first_block * comp.BLOCK, length)
        return info, gen, start, length
    note_get("fused" if fused else "legacy", length)
    if imeta.get(sse_mod.META_MULTIPART) and info.parts:
        gen = decrypt_parts_gen(ol, bucket, key, vid or info.version_id,
                                info, data_key, nonce, start, length)
        return info, gen, start, length
    first, c_off, c_len = package_range(start, length)
    c_size = encrypt_stream_size(info.size)
    c_len = min(c_len, c_size - c_off)
    _, raw = ol.get_object_stream(
        bucket, key, GetOptions(version_id=vid, offset=c_off,
                                length=c_len))
    chunks = decrypt_packages(raw, data_key, nonce, first,
                              start - first * PACKAGE_SIZE, length)
    return info, chunks, start, length


def decrypt_parts_gen(ol, bucket, key, vid, info, data_key, nonce,
                      start, length):
    """Plaintext range [start, start+length) across per-part DARE
    streams. Part boundaries in the STORED stream are the summed
    ciphertext part sizes; in the plaintext space the summed logical
    sizes. The whole covering stored range is fetched in ONE
    get_object_stream call — the per-part slices are contiguous (first
    part reads to its stored end, middles whole, last from its start),
    and a single read means a single version resolution, so a concurrent
    overwrite in an unversioned bucket cannot interleave versions
    mid-response. Each part decrypts under its derived key and its own
    stored base nonce."""
    import base64 as _b64

    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.crypto.dare import (PACKAGE_SIZE, decrypt_packages,
                                       package_range)
    # Plan: (part, first_seq, skip, plain_len, stored_lo, stored_len)
    plan = []
    pos, remaining = start, length
    plain_off = stored_off = 0
    for p in info.parts:
        if remaining <= 0:
            break
        if pos >= plain_off + p.actual_size:
            plain_off += p.actual_size
            stored_off += p.size
            continue
        in_off = pos - plain_off
        in_len = min(remaining, p.actual_size - in_off)
        first, c_off, c_len = package_range(in_off, in_len)
        c_len = min(c_len, p.size - c_off)
        plan.append((p, first, in_off - first * PACKAGE_SIZE,
                     in_len, stored_off + c_off, c_len))
        pos += in_len
        remaining -= in_len
        plain_off += p.actual_size
        stored_off += p.size
    if not plan:
        return
    lo = plan[0][4]
    hi = plan[-1][4] + plan[-1][5]
    _, raw = ol.get_object_stream(
        bucket, key, GetOptions(version_id=vid, offset=lo,
                                length=hi - lo))
    carry = bytearray()
    raw_iter = iter(raw)

    def take(n):
        """Yield exactly n bytes from the shared stored stream."""
        nonlocal carry
        while n > 0:
            if carry:
                chunk = bytes(carry[:n])
                del carry[:len(chunk)]
            else:
                try:
                    chunk = next(raw_iter)
                except StopIteration:
                    return       # decryptor reports the shortfall
                if len(chunk) > n:
                    carry.extend(chunk[n:])
                    chunk = chunk[:n]
            n -= len(chunk)
            yield chunk

    try:
        for p, first, skip, plain_len, _s_lo, s_len in plan:
            part_nonce = _b64.b64decode(p.nonce) if p.nonce else nonce
            yield from decrypt_packages(
                take(s_len), sse_mod.part_key(data_key, p.number),
                part_nonce, first, skip, plain_len)
    finally:
        close = getattr(raw, "close", None)
        if close is not None:
            close()


def plaintext_stream(ol, kms, bucket, key, vid="", h=None):
    """(info, chunks) for the object's LOGICAL bytes, whatever its
    stored representation — the one entry point for gateways that have
    no transform headers of their own (FTP, SFTP). SSE-C objects raise
    SSEError (the server holds no key for them).

    The transform re-open is pinned to the version the first open
    resolved; in UNVERSIONED buckets there is no version to pin, so a
    concurrent overwrite between the two reads can tear — the same
    small window the S3 GET path (and the reference) accepts there."""
    h = h or {}
    info, chunks = ol.get_object_stream(bucket, key,
                                        GetOptions(version_id=vid))
    imeta = info.internal_metadata
    if imeta.get("x-internal-sse-alg"):
        chunks.close()
        sse_check_head(h, info)
        info, chunks, _, _ = get_encrypted(
            ol, kms, bucket, key, vid or info.version_id, None, h, info)
    elif imeta.get("x-internal-comp"):
        chunks.close()
        info, chunks, _, _ = get_compressed(
            ol, bucket, key, vid or info.version_id, None, info)
    return info, chunks


def sse_payload(ol, kms, bucket, key, payload, opts, h=None):
    """Wrap a put payload in DARE encryption when the request headers
    (SSE-C / SSE-S3) or the bucket's default-encryption config ask for
    it — the single put-side SSE seam for every writer (reference:
    cmd/bucket-encryption.go consulted by the object API layer, not
    just the S3 handler). Returns (payload, response headers)."""
    from minio_tpu.crypto import EncryptingPayload, encrypt_stream_size
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.utils.streams import Payload
    h = h or {}
    customer = sse_mod.parse_sse_c(h)
    if customer is None:
        enc_cfg = ol.get_bucket_meta(bucket).get("config:encryption")
        if not sse_mod.wants_sse_s3(h, enc_cfg):
            return payload, {}
    payload = Payload.wrap(payload)
    data_key, nonce, imeta = sse_mod.encrypt_metadata(
        bucket, key, payload.size, kms, customer)
    opts.internal_metadata.update(imeta)
    enc = EncryptingPayload(payload, data_key, nonce)
    out = Payload(enc, encrypt_stream_size(payload.size))
    if customer is not None:
        return out, {sse_mod.H_C_ALG: "AES256",
                     sse_mod.H_C_MD5: customer[1]}
    return out, {sse_mod.H_SSE: "AES256"}
