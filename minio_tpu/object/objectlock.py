"""Object lock: WORM retention and legal holds.

The analogue of the reference's object-lock subsystem
(internal/bucket/object/lock/lock.go, enforced from
cmd/object-handlers.go:2705,2862 PutObjectRetentionHandler /
PutObjectLegalHoldHandler and cmd/erasure-object.go's delete checks):

- a bucket opts in at creation (`x-amz-bucket-object-lock-enabled`) or
  via PutObjectLockConfiguration; lock-enabled buckets are versioned
  and versioning can never be suspended on them;
- versions carry retention (GOVERNANCE | COMPLIANCE until a date) and
  an independent legal hold (ON | OFF), stored in version metadata;
- deleting a retained/held VERSION is refused; GOVERNANCE (only) can
  be bypassed by an identity holding s3:BypassGovernanceRetention via
  the `x-amz-bypass-governance-retention: true` header; COMPLIANCE
  retention can be extended but never shortened, by anyone.

Versionless deletes only stack a delete marker and are always allowed
(S3 semantics: the data stays, WORM is about version destruction).

Lock state lives in internal metadata keys so it never leaks into the
x-amz-meta-* user surface; the handlers translate to/from the
x-amz-object-lock-* wire headers.
"""

from __future__ import annotations

import datetime
import xml.etree.ElementTree as ET
from typing import Optional

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

GOVERNANCE = "GOVERNANCE"
COMPLIANCE = "COMPLIANCE"

# Internal metadata keys (PutOptions.internal_metadata requires the
# x-internal- prefix; _to_object_info routes them to internal_metadata).
META_MODE = "x-internal-lock-mode"
META_UNTIL = "x-internal-lock-until"      # ISO8601, as received
META_HOLD = "x-internal-lock-hold"        # "ON" | "OFF"

# Wire headers (PutObject / CreateMultipartUpload / responses).
H_MODE = "x-amz-object-lock-mode"
H_UNTIL = "x-amz-object-lock-retain-until-date"
H_HOLD = "x-amz-object-lock-legal-hold"
H_BYPASS = "x-amz-bypass-governance-retention"

# Bucket metadata key holding the lock configuration document.
BUCKET_META_KEY = "object_lock"


class ObjectLockError(Exception):
    """Maps to S3 error codes via `code`."""

    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(msg or code)


def parse_iso8601(s: str) -> int:
    """RetainUntilDate -> ns since epoch (S3 sends RFC3339/ISO8601)."""
    try:
        dt = datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except (ValueError, TypeError):
        raise ObjectLockError("InvalidArgument",
                              f"bad RetainUntilDate {s!r}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return int(dt.timestamp() * 1e9)


def _iso(ns: int) -> str:
    return datetime.datetime.fromtimestamp(
        ns / 1e9, tz=datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


# -- bucket configuration ---------------------------------------------------

def parse_lock_config_xml(body: bytes) -> dict:
    """<ObjectLockConfiguration> -> {"enabled": True, "mode"?, "days"?,
    "years"?}; validates like the reference (exactly one of Days/Years
    when a default-retention rule is present)."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ObjectLockError("MalformedXML") from None
    ns = f"{{{XMLNS}}}"

    def find(el, tag):
        return el.findtext(f"{ns}{tag}") or el.findtext(tag)

    enabled = find(root, "ObjectLockEnabled") or ""
    if enabled != "Enabled":
        raise ObjectLockError("MalformedXML",
                              "ObjectLockEnabled must be 'Enabled'")
    cfg: dict = {"enabled": True}
    rule = root.find(f"{ns}Rule")
    if rule is None:
        rule = root.find("Rule")
    if rule is not None:
        dr = rule.find(f"{ns}DefaultRetention")
        if dr is None:
            dr = rule.find("DefaultRetention")
        if dr is None:
            raise ObjectLockError("MalformedXML", "Rule needs "
                                  "DefaultRetention")
        mode = find(dr, "Mode") or ""
        if mode not in (GOVERNANCE, COMPLIANCE):
            raise ObjectLockError("MalformedXML", f"bad Mode {mode!r}")
        days, years = find(dr, "Days"), find(dr, "Years")
        if (days is None) == (years is None):
            raise ObjectLockError("MalformedXML",
                                  "exactly one of Days or Years")
        try:
            n = int(days if days is not None else years)
        except ValueError:
            raise ObjectLockError("MalformedXML", "bad Days/Years") from None
        if n <= 0:
            raise ObjectLockError("InvalidArgument",
                                  "retention period must be positive")
        cfg["mode"] = mode
        cfg["days" if days is not None else "years"] = n
    return cfg


def lock_config_xml(cfg: dict) -> bytes:
    root = ET.Element("ObjectLockConfiguration", xmlns=XMLNS)
    ET.SubElement(root, "ObjectLockEnabled").text = "Enabled"
    if cfg.get("mode"):
        rule = ET.SubElement(root, "Rule")
        dr = ET.SubElement(rule, "DefaultRetention")
        ET.SubElement(dr, "Mode").text = cfg["mode"]
        if "days" in cfg:
            ET.SubElement(dr, "Days").text = str(cfg["days"])
        else:
            ET.SubElement(dr, "Years").text = str(cfg["years"])
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def default_retention_meta(cfg: dict, now_ns: int) -> dict:
    """Bucket default retention -> internal metadata for a new version
    (reference: lock.FilterObjectLockMetadata + default application at
    PUT, cmd/api-headers.go)."""
    if not cfg or not cfg.get("mode"):
        return {}
    days = cfg.get("days", 0) + 365 * cfg.get("years", 0)
    until = now_ns + days * 86400 * 10**9
    return {META_MODE: cfg["mode"], META_UNTIL: _iso(until)}


# -- per-version state ------------------------------------------------------

def headers_to_meta(h: dict, lock_enabled: bool, now_ns: int) -> dict:
    """x-amz-object-lock-* request headers -> internal metadata.
    Raises unless the bucket has object lock enabled (the reference
    refuses lock headers on unlocked buckets)."""
    mode = h.get(H_MODE, "")
    until = h.get(H_UNTIL, "")
    hold = h.get(H_HOLD, "")
    if not (mode or until or hold):
        return {}
    if not lock_enabled:
        raise ObjectLockError("InvalidRequest",
                              "bucket is missing ObjectLockConfiguration")
    out: dict = {}
    if mode or until:
        if mode not in (GOVERNANCE, COMPLIANCE) or not until:
            raise ObjectLockError("InvalidArgument",
                                  "lock mode and retain-until-date must "
                                  "both be set")
        if parse_iso8601(until) <= now_ns:
            raise ObjectLockError("InvalidArgument",
                                  "RetainUntilDate must be in the future")
        out[META_MODE] = mode
        out[META_UNTIL] = until
    if hold:
        if hold not in ("ON", "OFF"):
            raise ObjectLockError("InvalidArgument",
                                  f"bad legal hold {hold!r}")
        out[META_HOLD] = hold
    return out


def meta_to_headers(imeta: dict) -> dict:
    out = {}
    if imeta.get(META_MODE):
        out[H_MODE] = imeta[META_MODE]
        out[H_UNTIL] = imeta.get(META_UNTIL, "")
    if imeta.get(META_HOLD):
        out[H_HOLD] = imeta[META_HOLD]
    return out


def retention_xml(imeta: dict) -> bytes:
    root = ET.Element("Retention", xmlns=XMLNS)
    if imeta.get(META_MODE):
        ET.SubElement(root, "Mode").text = imeta[META_MODE]
        ET.SubElement(root, "RetainUntilDate").text = \
            imeta.get(META_UNTIL, "")
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def parse_retention_xml(body: bytes) -> tuple[str, str]:
    """-> (mode, until_iso); ("", "") clears (empty doc)."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ObjectLockError("MalformedXML") from None
    ns = f"{{{XMLNS}}}"
    mode = root.findtext(f"{ns}Mode") or root.findtext("Mode") or ""
    until = root.findtext(f"{ns}RetainUntilDate") or \
        root.findtext("RetainUntilDate") or ""
    if not mode and not until:
        return "", ""
    if mode not in (GOVERNANCE, COMPLIANCE):
        raise ObjectLockError("MalformedXML", f"bad Mode {mode!r}")
    if not until:
        raise ObjectLockError("MalformedXML", "missing RetainUntilDate")
    parse_iso8601(until)
    return mode, until


def legal_hold_xml(imeta: dict) -> bytes:
    root = ET.Element("LegalHold", xmlns=XMLNS)
    ET.SubElement(root, "Status").text = imeta.get(META_HOLD) or "OFF"
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def parse_legal_hold_xml(body: bytes) -> str:
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise ObjectLockError("MalformedXML") from None
    ns = f"{{{XMLNS}}}"
    status = root.findtext(f"{ns}Status") or root.findtext("Status") or ""
    if status not in ("ON", "OFF"):
        raise ObjectLockError("MalformedXML", f"bad Status {status!r}")
    return status


# -- enforcement ------------------------------------------------------------

def retained_until(imeta: dict) -> int:
    """Active retention deadline in ns, 0 if none/expired-irrelevant."""
    if not imeta.get(META_MODE):
        return 0
    try:
        return parse_iso8601(imeta.get(META_UNTIL, ""))
    except ObjectLockError:
        # Unparseable stored date: treat as retained forever rather
        # than silently unprotected.
        return 1 << 62


def check_version_deletable(imeta: dict, now_ns: int,
                            bypass_governance: bool) -> Optional[str]:
    """None if the version may be destroyed, else the S3 error code
    (reference: enforceRetentionForDeletion,
    cmd/bucket-object-lock.go)."""
    if imeta.get(META_HOLD) == "ON":
        return "AccessDenied"
    mode = imeta.get(META_MODE)
    if not mode:
        return None
    if retained_until(imeta) <= now_ns:
        return None
    if mode == GOVERNANCE and bypass_governance:
        return None
    return "AccessDenied"


def check_retention_change(imeta: dict, new_mode: str, new_until: str,
                           now_ns: int,
                           bypass_governance: bool) -> Optional[str]:
    """May the version's retention be set to (new_mode, new_until)?
    COMPLIANCE only ever extends; GOVERNANCE shrinks/clears only with
    bypass (reference: checkPutObjectRetentionAllowed,
    cmd/object-handlers.go:2705)."""
    cur_mode = imeta.get(META_MODE)
    cur_until = retained_until(imeta)
    if not cur_mode or cur_until <= now_ns:
        return None                       # nothing active: any change ok
    new_ns = parse_iso8601(new_until) if new_until else 0
    if cur_mode == COMPLIANCE:
        # Extension in COMPLIANCE is the single permitted change.
        if new_mode == COMPLIANCE and new_ns >= cur_until:
            return None
        return "AccessDenied"
    # GOVERNANCE: strengthening to a later date is fine; anything else
    # (shorten, clear, mode change) needs the bypass permission.
    if new_mode == GOVERNANCE and new_ns >= cur_until:
        return None
    return None if bypass_governance else "AccessDenied"
