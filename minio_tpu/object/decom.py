"""Pool decommission: drain one pool's objects into the others.

The analogue of the reference's erasure-server-pool decommissioning
(cmd/erasure-server-pool-decom.go:1269 decommissionPool + its
checkpointed resume): an admin marks a pool for draining; a background
worker walks every bucket and migrates each object's FULL version stack
(data versions re-encoded into the destination's geometry, delete
markers preserved, metadata/etags/part boundaries byte-identical via
ErasureSet.restore_version) into the remaining pools, then deletes the
source copy. Progress checkpoints persist on the SURVIVING pools'
drives, so a crashed or restarted server resumes where it left off
(the reference persists decomState in pool.bin the same way).

While a drain runs:
- new writes place in non-decommissioning pools (ServerPools excludes
  the pool from placement);
- reads keep succeeding: the version stack is restored to the
  destination BEFORE the source copy is deleted, and pool search
  visits destinations first, so every moment of the migration has the
  key readable somewhere.

When the walk completes the pool is marked "complete"; the operator
restarts the server without the drained pool's endpoints.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from minio_tpu.storage.local import SYS_VOL

DECOM_PATH = "config/decom.json"
CHECKPOINT_EVERY = 16          # objects between checkpoint persists


class DecomError(Exception):
    pass


def pool_signature(pool) -> str:
    """Stable identity for a pool: hash of its sorted drive endpoints.
    Pool INDICES shift when the operator removes the drained pool from
    the topology; a persisted index would then point at a live pool and
    exclude it from placement forever."""
    import hashlib
    ids = []
    for s in pool.sets:
        for d in s.disks:
            ids.append(getattr(d, "endpoint", "") or
                       getattr(d, "root", ""))
    return hashlib.sha256("\n".join(sorted(ids)).encode()).hexdigest()[:16]


def find_pool_by_signature(pools_layer, sig: str):
    """Current index of the pool with this signature, or None (the
    pool was removed from the topology)."""
    for i, p in enumerate(pools_layer.pools):
        if pool_signature(p) == sig:
            return i
    return None


def _state_disks(pools_layer, skip_idx: int):
    """Drives of the first LIVE surviving pool — the state must not
    live on the pool being removed, NOR on a previously drained pool
    (its removal would take the active drain's only record with it)."""
    decom = getattr(pools_layer, "decommissioning", set())
    for i, p in enumerate(pools_layer.pools):
        if i != skip_idx and i not in decom:
            return [d for s in p.sets for d in s.disks]
    # Fallback (e.g. status queries after every other pool completed):
    # any pool other than the drained one.
    for i, p in enumerate(pools_layer.pools):
        if i != skip_idx:
            return [d for s in p.sets for d in s.disks]
    raise DecomError("cannot decommission the only pool")


def load_doc(pools_layer) -> dict:
    """The decommission document: every drain's record keyed by pool
    SIGNATURE, monotonically revisioned (sequential decommissions must
    not shadow each other's records — a single per-drain doc left a
    stale copy on the earlier drain's destination that could win the
    read after a restart). Picks the highest-revision copy across
    pools."""
    best: Optional[dict] = None
    for p in pools_layer.pools:
        votes: dict[bytes, int] = {}
        for s in p.sets:
            for d in s.disks:
                try:
                    blob = d.read_all(SYS_VOL, DECOM_PATH)
                    votes[blob] = votes.get(blob, 0) + 1
                except Exception:  # noqa: BLE001 - absent / offline
                    continue
        if not votes:
            continue
        blob = max(votes.items(), key=lambda kv: kv[1])[0]
        try:
            doc = json.loads(blob)
        except ValueError:
            continue
        if isinstance(doc, dict) and "records" in doc and \
                (best is None or doc.get("rev", 0) > best.get("rev", 0)):
            best = doc
    return best if best is not None else {"records": {}, "rev": 0}


def load_state(pools_layer) -> Optional[dict]:
    """The most recent drain's record (None when none was ever
    started) — the admin-status and test surface."""
    records = load_doc(pools_layer).get("records", {})
    if not records:
        return None
    return max(records.values(), key=lambda r: r.get("started_ns", 0))


def _write_doc(pools_layer, doc: dict, skip_idx: int,
               scrub: bool = False) -> None:
    """Quorum-write the document to the first surviving pool; `scrub`
    deletes stale copies on other pools (needed once per drain — the
    doc carries EVERY record, so scrubbed pools lose nothing)."""
    blob = json.dumps(doc, sort_keys=True).encode()
    disks = _state_disks(pools_layer, skip_idx)
    ok = 0
    for d in disks:
        try:
            d.write_all(SYS_VOL, DECOM_PATH, blob)
            ok += 1
        except Exception:  # noqa: BLE001 - offline drive
            continue
    if ok < len(disks) // 2 + 1:
        raise DecomError("could not persist decommission state to a quorum")
    if scrub:
        keep = {id(d) for d in disks}
        for p in pools_layer.pools:
            for s in p.sets:
                for d in s.disks:
                    if id(d) in keep:
                        continue
                    try:
                        d.delete(SYS_VOL, DECOM_PATH)
                    except Exception:  # noqa: BLE001 - absent / offline
                        pass


def _save_state(pools_layer, state: dict) -> None:
    """Load-upsert-write for callers without a cached doc."""
    doc = load_doc(pools_layer)
    doc["records"][state["pool_sig"]] = state
    doc["rev"] = doc.get("rev", 0) + 1
    _write_doc(pools_layer, doc, state["pool"], scrub=True)


def migrate_key(layer, src_idx: int, bucket: str, key: str,
                pick_dst) -> None:
    """Move one key's whole version stack out of pool `src_idx` — the
    transfer primitive shared by decommission and rebalance.

    Shape: snapshot → restore (no locks held across sets — in
    distributed mode src and dst share the cluster-wide per-key
    lock resource, so nesting them would deadlock) → verify +
    clean up under the source key lock. Versions restore NEWEST
    FIRST so the destination's latest-version resolution (markers
    included) is correct at every intermediate step. Inside the
    locked verify, versions that were deleted during the copy are
    removed from the destination too (the API routes version
    deletes to every pool while a drain runs), so an acknowledged
    delete can never resurrect; the source copies are destroyed
    only after everything landed — reads never see the key absent.

    pick_dst() chooses the destination pool index when no existing
    stack pins one.
    """
    from minio_tpu.object.types import (DeleteOptions, GetOptions,
                                        MethodNotAllowed,
                                        ObjectNotFound, VersionNotFound)
    src_set = layer.pools[src_idx].set_for(key)
    # Destination pinning: if another eligible pool already holds this
    # key (e.g. a concurrent overwrite placed a new version there),
    # the old versions must join that same stack — a free-space
    # choice could split the key across two pools, and pool-ordered
    # reads would then shadow the newer write.
    dst_idx = layer._pool_of_existing(bucket, key)
    if dst_idx is None or dst_idx == src_idx or \
            dst_idx in layer.decommissioning:
        dst_idx = pick_dst()
    dst_set = layer.pools[dst_idx].set_for(key)
    for _attempt in range(5):
        try:
            versions = src_set.list_versions_all(bucket, key)
        except ObjectNotFound:
            return                  # deleted mid-walk: nothing to do
        from minio_tpu.object.tier import META_TIER
        for fi in sorted(versions, key=lambda f: -f.mod_time):
            data = None
            tiered = bool((fi.metadata or {}).get(META_TIER))
            if not fi.deleted and not tiered:
                # Tiered versions migrate pointer-only — their
                # data stays in the warm tier.
                try:
                    _, data = src_set.get_object(
                        bucket, key,
                        GetOptions(version_id=fi.version_id))
                except (VersionNotFound, MethodNotAllowed,
                        ObjectNotFound):
                    continue        # pruned mid-walk
            # skip_if_newer_null: a concurrent unversioned
            # overwrite placed a NEWER null version in the
            # destination; the check runs inside restore_version's
            # key lock so the decision and the write are atomic.
            dst_set.restore_version(bucket, key, fi, data,
                                    skip_if_newer_null=True)
        with src_set.ns.write(bucket, key):
            try:
                cur = src_set.list_versions_all(bucket, key)
            except ObjectNotFound:
                cur = []
            snap_ids = {v.version_id for v in versions}
            cur_ids = {v.version_id for v in cur}
            if not cur_ids <= snap_ids:
                continue            # stack changed mid-copy: redo
            for vid in snap_ids - cur_ids:
                # Deleted from the source while we copied: the
                # restored destination copy must go too (unlocked
                # internal — this thread holds the key lock).
                try:
                    dst_set._delete_object_locked(
                        bucket, key, DeleteOptions(
                            version_id=vid, versioned=False))
                except (ObjectNotFound, VersionNotFound):
                    pass
            for fi in cur:
                try:
                    src_set._delete_object_locked(
                        bucket, key, DeleteOptions(
                            version_id=fi.version_id,
                            versioned=False))
                except (ObjectNotFound, VersionNotFound):
                    pass
            return
    raise DecomError(f"{bucket}/{key}: version stack kept changing")


class Decommission:
    """One pool-drain driver (start fresh or resume from a checkpoint)."""

    def __init__(self, pools_layer, pool_idx: int,
                 state: Optional[dict] = None,
                 checkpoint_every: int = CHECKPOINT_EVERY):
        if not 0 <= pool_idx < len(pools_layer.pools):
            raise DecomError(f"no pool {pool_idx}")
        survivors = [i for i in range(len(pools_layer.pools))
                     if i != pool_idx
                     and i not in pools_layer.decommissioning]
        if not survivors:
            # Draining the last non-draining pool would wedge every
            # write in the cluster with nowhere to place objects.
            raise DecomError("no surviving pool to drain into")
        self.layer = pools_layer
        self.pool_idx = pool_idx
        self.checkpoint_every = checkpoint_every
        self.state = state or {
            "pool": pool_idx, "status": "draining",
            "pool_sig": pool_signature(pools_layer.pools[pool_idx]),
            "started_ns": time.time_ns(),
            "bucket": "", "marker": "",        # resume checkpoint
            "migrated": 0, "failed": 0,
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The decom document, loaded once: checkpoints must not pay a
        # cluster-wide read + scrub every few objects on the hot path.
        self._doc: Optional[dict] = None

    # -- control ---------------------------------------------------------

    def _notify_peers(self) -> None:
        """Status transitions fan out so peer nodes re-sync their
        placement-exclusion sets immediately (reference: decom updates
        ride the notification system too); checkpoint saves don't —
        they change no placement decision."""
        cb = getattr(self.layer, "on_decom_change", None)
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 - fan-out must not fail drain
                pass

    def _persist(self, scrub: bool = False) -> None:
        """Write progress using the driver's cached document — the
        checkpoint hot path must not re-read every drive in the
        cluster (the doc is only mutated by the single active drain)."""
        if self._doc is None:
            self._doc = load_doc(self.layer)
        self._doc["records"][self.state["pool_sig"]] = self.state
        self._doc["rev"] = self._doc.get("rev", 0) + 1
        _write_doc(self.layer, self._doc, self.pool_idx, scrub=scrub)

    def start(self) -> None:
        self.layer.decommissioning.add(self.pool_idx)
        self._persist(scrub=True)
        self._notify_peers()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"decom-pool{self.pool_idx}")
        self._thread.start()

    def stop(self) -> None:
        """Pause the drain (state stays 'draining'; a resume picks up
        from the last checkpoint). Persists the current progress so a
        clean pause loses nothing — only a hard crash falls back to
        the periodic checkpoint."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self.state.get("status") == "draining":
            try:
                self._persist()
            except DecomError:
                pass

    def wait(self, timeout: float = 300) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    # -- the drain -------------------------------------------------------

    def _run(self) -> None:
        try:
            self._drain()
        except Exception as e:  # noqa: BLE001 - recorded, resumable
            self.state["status"] = "failed"
            self.state["error"] = str(e)
            try:
                self._persist()
            except DecomError:
                pass

    def _drain(self) -> None:
        src = self.layer.pools[self.pool_idx]
        since_ckpt = 0
        buckets = sorted(b.name for b in src.list_buckets())
        # Resume: skip buckets already fully drained.
        start_bucket = self.state.get("bucket", "")
        for bucket in buckets:
            if bucket < start_bucket:
                continue
            marker = self.state.get("marker", "") \
                if bucket == start_bucket else ""
            while not self._stop.is_set():
                page = src.list_objects(bucket, marker=marker,
                                        max_keys=256,
                                        include_versions=True)
                keys = sorted({o.name for o in page.objects})
                for key in keys:
                    if self._stop.is_set():
                        return
                    try:
                        self._migrate_key(src, bucket, key)
                        self.state["migrated"] += 1
                    except Exception as e:  # noqa: BLE001 - keep going
                        self.state["failed"] += 1
                        self.state["last_error"] = f"{bucket}/{key}: {e}"
                    # Track progress after every key (a clean stop()
                    # persists it exactly); hit the drives only every
                    # checkpoint_every keys.
                    self.state["bucket"] = bucket
                    self.state["marker"] = key
                    since_ckpt += 1
                    if since_ckpt >= self.checkpoint_every:
                        since_ckpt = 0
                        self._persist()
                if not page.is_truncated:
                    break
                marker = page.next_marker
            if self._stop.is_set():
                return
            self.state["bucket"] = bucket
            self.state["marker"] = ""
            self._persist()
        if self.state["failed"]:
            self.state["status"] = "failed"
        else:
            self.state["status"] = "complete"
            self.state["finished_ns"] = time.time_ns()
        self._persist()
        self._notify_peers()

    def _migrate_key(self, src_pool, bucket: str, key: str) -> None:
        migrate_key(self.layer, self.pool_idx, bucket, key, self._dst_idx)

    def _dst_idx(self) -> int:
        """Surviving pool with the most free space (the reference picks
        by available capacity too)."""
        best, best_free = None, -1
        for i, p in enumerate(self.layer.pools):
            if i == self.pool_idx or i in self.layer.decommissioning:
                continue
            free = p.free_space()
            if free > best_free:
                best, best_free = i, free
        if best is None:
            raise DecomError("no destination pool available")
        return best
