"""Pool decommission: drain one pool's objects into the others.

The analogue of the reference's erasure-server-pool decommissioning
(cmd/erasure-server-pool-decom.go:1269 decommissionPool + its
checkpointed resume): an admin marks a pool for draining; a background
worker walks every bucket and migrates each object's FULL version stack
(data versions re-encoded into the destination's geometry, delete
markers preserved, metadata/etags/part boundaries byte-identical via
ErasureSet.restore_version) into the remaining pools, then deletes the
source copy. Progress checkpoints persist on the SURVIVING pools'
drives, so a crashed or restarted server resumes where it left off
(the reference persists decomState in pool.bin the same way).

While a drain runs:
- new writes place in non-decommissioning pools (ServerPools excludes
  the pool from placement);
- reads keep succeeding: the version stack is restored to the
  destination BEFORE the source copy is deleted, and pool search
  visits destinations first, so every moment of the migration has the
  key readable somewhere.

When the walk completes the pool is marked "complete"; the operator
restarts the server without the drained pool's endpoints.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from minio_tpu.storage.local import SYS_VOL
from minio_tpu.utils.env import env_float, env_int

DECOM_PATH = "config/decom.json"
CHECKPOINT_EVERY = 16          # objects between checkpoint persists


class DecomError(Exception):
    pass


class LeaseHeld(DecomError):
    """Another node holds the migration coordinator lease: the drain
    (or rebalance) is already being driven from there."""


class MigrationGovernor:
    """Admission integration for migration traffic: the drain/rebalance
    walk is a BACKGROUND class that yields to foreground SLOs.

    gate() blocks while the front end is visibly queueing (the same
    pressure signal drive_heal's bulk heal sheds on — see
    drive_heal.admission_pressure wired via layer.migration_pressure),
    counting each pause into state["yields"]. Knobs:

      MTPU_REBALANCE_WORKERS   concurrent migrate workers per pool walk
                               (default 1: strictly ordered)
      MTPU_REBALANCE_YIELD_MS  pressure poll interval while yielded,
                               and the fixed pacing delay per key when
                               > 0 and no pressure (default 50 / 0)
    """

    def __init__(self, layer, state: dict, stop: threading.Event):
        self.pressure: Optional[Callable[[], bool]] = \
            getattr(layer, "migration_pressure", None)
        self.poll_s = max(1.0, env_float("MTPU_REBALANCE_YIELD_MS",
                                         50.0)) / 1000.0
        self.pace_s = env_float("MTPU_REBALANCE_PACE_MS", 0.0) / 1000.0
        self.workers = max(1, env_int("MTPU_REBALANCE_WORKERS", 1))
        self.state = state
        self._stop = stop
        self._mu = threading.Lock()

    def count(self, key: str, by: int = 1) -> None:
        """Thread-safe state counter bump (workers > 1 share state)."""
        self.add(self.state, key, by)

    def add(self, rec: dict, key: str, by: int = 1) -> None:
        """Same, for a caller-chosen record (rebalance keeps per-pool
        records inside its state doc)."""
        with self._mu:
            rec[key] = rec.get(key, 0) + by

    def gate(self) -> bool:
        """Pause while foreground clients queue; False = stop fired
        (the caller checkpoints and returns)."""
        p = self.pressure
        yielded = False
        while p is not None and p():
            if self._stop.is_set():
                return False
            if not yielded:
                yielded = True
                self.count("yields")
            time.sleep(self.poll_s)
        if self.pace_s > 0:
            time.sleep(self.pace_s)
        return not self._stop.is_set()


def pool_signature(pool) -> str:
    """Stable identity for a pool: hash of its sorted drive endpoints.
    Pool INDICES shift when the operator removes the drained pool from
    the topology; a persisted index would then point at a live pool and
    exclude it from placement forever."""
    import hashlib
    ids = []
    for s in pool.sets:
        for d in s.disks:
            ids.append(getattr(d, "endpoint", "") or
                       getattr(d, "root", ""))
    return hashlib.sha256("\n".join(sorted(ids)).encode()).hexdigest()[:16]


def find_pool_by_signature(pools_layer, sig: str):
    """Current index of the pool with this signature, or None (the
    pool was removed from the topology)."""
    for i, p in enumerate(pools_layer.pools):
        if pool_signature(p) == sig:
            return i
    return None


def _state_disks(pools_layer, skip_idx: int):
    """Drives of the first LIVE surviving pool — the state must not
    live on the pool being removed, NOR on a previously drained pool
    (its removal would take the active drain's only record with it)."""
    decom = getattr(pools_layer, "decommissioning", set())
    for i, p in enumerate(pools_layer.pools):
        if i != skip_idx and i not in decom:
            return [d for s in p.sets for d in s.disks]
    # Fallback (e.g. status queries after every other pool completed):
    # any pool other than the drained one.
    for i, p in enumerate(pools_layer.pools):
        if i != skip_idx:
            return [d for s in p.sets for d in s.disks]
    raise DecomError("cannot decommission the only pool")


def load_doc(pools_layer) -> dict:
    """The decommission document: every drain's record keyed by pool
    SIGNATURE, monotonically revisioned (sequential decommissions must
    not shadow each other's records — a single per-drain doc left a
    stale copy on the earlier drain's destination that could win the
    read after a restart). Picks the highest-revision copy across
    pools."""
    best: Optional[dict] = None
    for p in pools_layer.pools:
        votes: dict[bytes, int] = {}
        for s in p.sets:
            for d in s.disks:
                try:
                    blob = d.read_all(SYS_VOL, DECOM_PATH)
                    votes[blob] = votes.get(blob, 0) + 1
                except Exception:  # noqa: BLE001 - absent / offline
                    continue
        if not votes:
            continue
        blob = max(votes.items(), key=lambda kv: kv[1])[0]
        try:
            doc = json.loads(blob)
        except ValueError:
            continue
        if isinstance(doc, dict) and "records" in doc and \
                (best is None or doc.get("rev", 0) > best.get("rev", 0)):
            best = doc
    return best if best is not None else {"records": {}, "rev": 0}


def load_state(pools_layer) -> Optional[dict]:
    """The most recent drain's record (None when none was ever
    started) — the admin-status and test surface."""
    records = load_doc(pools_layer).get("records", {})
    if not records:
        return None
    return max(records.values(), key=lambda r: r.get("started_ns", 0))


def _write_doc(pools_layer, doc: dict, skip_idx: int,
               scrub: bool = False) -> None:
    """Quorum-write the document to the first surviving pool; `scrub`
    deletes stale copies on other pools (needed once per drain — the
    doc carries EVERY record, so scrubbed pools lose nothing)."""
    blob = json.dumps(doc, sort_keys=True).encode()
    disks = _state_disks(pools_layer, skip_idx)
    ok = 0
    for d in disks:
        try:
            d.write_all(SYS_VOL, DECOM_PATH, blob)
            ok += 1
        except Exception:  # noqa: BLE001 - offline drive
            continue
    if ok < len(disks) // 2 + 1:
        raise DecomError("could not persist decommission state to a quorum")
    if scrub:
        keep = {id(d) for d in disks}
        for p in pools_layer.pools:
            for s in p.sets:
                for d in s.disks:
                    if id(d) in keep:
                        continue
                    try:
                        d.delete(SYS_VOL, DECOM_PATH)
                    except Exception:  # noqa: BLE001 - absent / offline
                        pass


def _save_state(pools_layer, state: dict) -> None:
    """Load-upsert-write for callers without a cached doc."""
    doc = load_doc(pools_layer)
    doc["records"][state["pool_sig"]] = state
    doc["rev"] = doc.get("rev", 0) + 1
    _write_doc(pools_layer, doc, state["pool"], scrub=True)


def coordinator_lease(layer, name: str):
    """dsync write lease electing THE single fleet-wide coordinator
    for a migration (`decom` / `rebalance`). Returns None when the
    layer has no lockers (single-node deployments need no election).

    The lease auto-refreshes while held; a SIGKILLed coordinator stops
    refreshing and the LockServer TTL (MTPU_GRID_LOCK_TTL) expires its
    entry, after which any surviving node's elastic janitor wins the
    lock and resumes the walk from the persisted checkpoint."""
    lockers = getattr(layer, "lockers", None)
    if not lockers:
        return None
    from minio_tpu.grid.dsync import DRWMutex
    return DRWMutex(lockers, f"{SYS_VOL}/elastic/{name}-coordinator")


def migrate_key(layer, src_idx: int, bucket: str, key: str,
                pick_dst) -> int:
    """Move one key's whole version stack out of pool `src_idx` — the
    transfer primitive shared by decommission and rebalance.
    Returns the number of data bytes restored into the destination.

    Shape: snapshot → restore (no locks held across sets — in
    distributed mode src and dst share the cluster-wide per-key
    lock resource, so nesting them would deadlock) → verify +
    clean up under the source key lock. Versions restore NEWEST
    FIRST so the destination's latest-version resolution (markers
    included) is correct at every intermediate step. Inside the
    locked verify, versions that were deleted during the copy are
    removed from the destination too (the API routes version
    deletes to every pool while a drain runs), so an acknowledged
    delete can never resurrect; the source copies are destroyed
    only after everything landed — reads never see the key absent.

    pick_dst() chooses the destination pool index when no existing
    stack pins one.
    """
    from minio_tpu.object.types import (DeleteOptions, GetOptions,
                                        MethodNotAllowed,
                                        ObjectNotFound, VersionNotFound)
    src_set = layer.pools[src_idx].set_for(key)
    # Destination pinning: if another eligible pool already holds this
    # key (e.g. a concurrent overwrite placed a new version there),
    # the old versions must join that same stack — a free-space
    # choice could split the key across two pools, and pool-ordered
    # reads would then shadow the newer write.
    dst_idx = layer._pool_of_existing(bucket, key)
    if dst_idx is None or dst_idx == src_idx or \
            dst_idx in layer.decommissioning:
        dst_idx = pick_dst()
    dst_set = layer.pools[dst_idx].set_for(key)
    for _attempt in range(5):
        moved = 0
        try:
            versions = src_set.list_versions_all(bucket, key)
        except ObjectNotFound:
            return 0                # deleted mid-walk: nothing to do
        from minio_tpu.object.tier import META_TIER
        for fi in sorted(versions, key=lambda f: -f.mod_time):
            data = None
            tiered = bool((fi.metadata or {}).get(META_TIER))
            if not fi.deleted and not tiered:
                # Tiered versions migrate pointer-only — their
                # data stays in the warm tier.
                try:
                    _, data = src_set.get_object(
                        bucket, key,
                        GetOptions(version_id=fi.version_id))
                except (VersionNotFound, MethodNotAllowed,
                        ObjectNotFound):
                    continue        # pruned mid-walk
            # skip_if_newer_null: a concurrent unversioned
            # overwrite placed a NEWER null version in the
            # destination; the check runs inside restore_version's
            # key lock so the decision and the write are atomic.
            dst_set.restore_version(bucket, key, fi, data,
                                    skip_if_newer_null=True)
            if data is not None:
                moved += len(data)
        # Cross-node coherence: peers may hold a cached GET/HEAD
        # (fi_cache) or listing page resolved against the SOURCE copy.
        # Bump the bucket generation — broadcast-acked in distributed
        # mode — BEFORE any source copy is destroyed, so no node keeps
        # serving the migrated-away copy from cache after the cleanup
        # below lands (a re-fill in the gap resolves destination-first
        # and is already correct).
        mc = getattr(src_set, "metacache", None)
        if mc is not None:
            mc.bump(bucket)
        with src_set.ns.write(bucket, key):
            try:
                cur = src_set.list_versions_all(bucket, key)
            except ObjectNotFound:
                cur = []
            snap_ids = {v.version_id for v in versions}
            cur_ids = {v.version_id for v in cur}
            if not cur_ids <= snap_ids:
                continue            # stack changed mid-copy: redo
            for vid in snap_ids - cur_ids:
                # Deleted from the source while we copied: the
                # restored destination copy must go too (unlocked
                # internal — this thread holds the key lock).
                try:
                    dst_set._delete_object_locked(
                        bucket, key, DeleteOptions(
                            version_id=vid, versioned=False))
                except (ObjectNotFound, VersionNotFound):
                    pass
            for fi in cur:
                try:
                    src_set._delete_object_locked(
                        bucket, key, DeleteOptions(
                            version_id=fi.version_id,
                            versioned=False))
                except (ObjectNotFound, VersionNotFound):
                    pass
            return moved
    raise DecomError(f"{bucket}/{key}: version stack kept changing")


def _free_space_dst(layer, exclude: set) -> int:
    """Surviving pool with the most free space, skipping `exclude` and
    anything decommissioning (shared by decom and rebalance shards)."""
    best, best_free = None, -1
    for i, p in enumerate(layer.pools):
        if i in exclude or i in layer.decommissioning:
            continue
        free = p.free_space()
        if free > best_free:
            best, best_free = i, free
    if best is None:
        raise DecomError("no destination pool available")
    return best


def exec_page(layer, src_idx: int, bucket: str, keys: list,
              exclude=()) -> dict:
    """One fleet-sharded migration batch executed on THIS node — the
    body of the ``mig.page`` grid verb. Migrates `keys` out of pool
    `src_idx`, yielding to local foreground pressure between keys, and
    returns aggregate counters ONLY ({migrated, failed, bytes,
    last_error}): the coordinator owns every checkpoint write, so a
    peer crash mid-batch loses nothing but that batch's work (the
    coordinator re-walks the page; migrate_key is idempotent)."""
    ex = set(int(i) for i in exclude) | {int(src_idx)}
    pressure = getattr(layer, "migration_pressure", None)
    poll_s = max(1.0, env_float("MTPU_REBALANCE_YIELD_MS", 50.0)) / 1000.0
    out = {"migrated": 0, "failed": 0, "bytes": 0, "last_error": None}
    for key in keys:
        while pressure is not None and pressure():
            time.sleep(poll_s)
        try:
            moved = migrate_key(layer, src_idx, bucket, key,
                                lambda: _free_space_dst(layer, ex))
            out["migrated"] += 1
            out["bytes"] += int(moved or 0)
        except Exception as e:  # noqa: BLE001 - keep going, report
            out["failed"] += 1
            out["last_error"] = f"{bucket}/{key}: {e}"
    return out


class PageDispatcher:
    """Fleet-sharded migration walk (N nodes): the coordinator shards
    each listing page's keys across the cluster by stable key hash —
    one shard stays local, the rest ship to peer nodes as ``mig.page``
    grid calls executed against each peer's OWN pools layer — and
    aggregates the returned counters. Peers write no state: the
    coordinator alone checkpoints, so resume/crash semantics are
    exactly the single-walker ones. A peer that is down, partitioned,
    or running an older build (NoSuchHandler) gets its shard migrated
    locally — fleet width is a throughput optimization, never a
    correctness dependency."""

    def __init__(self, layer, peers, timeout: Optional[float] = None):
        self.layer = layer
        self.peers = list(peers)
        self.timeout = timeout if timeout is not None else \
            env_float("MTPU_MIG_PAGE_TIMEOUT_S", 600.0)

    def run(self, src_idx: int, bucket: str, keys: list,
            exclude=()) -> dict:
        import zlib
        n = len(self.peers) + 1
        shards: list[list] = [[] for _ in range(n)]
        for k in keys:
            shards[zlib.crc32(k.encode()) % n].append(k)
        agg = {"migrated": 0, "failed": 0, "bytes": 0, "last_error": None}
        agg_mu = threading.Lock()
        ex = sorted(set(int(i) for i in exclude) | {int(src_idx)})

        def merge(res: dict) -> None:
            with agg_mu:
                agg["migrated"] += int(res.get("migrated", 0))
                agg["failed"] += int(res.get("failed", 0))
                agg["bytes"] += int(res.get("bytes", 0))
                if res.get("last_error"):
                    agg["last_error"] = res["last_error"]

        def remote(i: int, shard: list) -> None:
            try:
                res = self.peers[i].call(
                    "mig.page", {"src": src_idx, "b": bucket,
                                 "keys": shard, "ex": ex},
                    timeout=self.timeout)
            except Exception:  # noqa: BLE001 - peer down: do it here
                res = exec_page(self.layer, src_idx, bucket, shard, ex)
            merge(res)

        threads = [threading.Thread(target=remote, args=(i, shard),
                                    daemon=True,
                                    name=f"mig-page-peer{i}")
                   for i, shard in enumerate(shards[1:]) if shard]
        for t in threads:
            t.start()
        if shards[0]:
            merge(exec_page(self.layer, src_idx, bucket, shards[0], ex))
        for t in threads:
            t.join()
        return agg

    def iter_batches(self, src_idx: int, bucket: str, keys: list,
                     exclude=(), gate=None):
        """Ordered batches of `keys` (MTPU_MIG_BATCH per fleet node
        each), hash-sharded across the fleet with a barrier per batch,
        yielding (batch, counters): the caller advances its marker and
        checkpoints BETWEEN batches, so progress stays observable and
        a crashed coordinator re-walks one batch, not one page. `gate`
        (the governor's) runs before each batch — pressure yield,
        pacing, stop."""
        per_node = max(1, env_int("MTPU_MIG_BATCH", 8))
        width = per_node * (len(self.peers) + 1)
        for i in range(0, len(keys), width):
            if gate is not None and not gate():
                return
            batch = keys[i:i + width]
            yield batch, self.run(src_idx, bucket, batch, exclude)


def page_dispatcher(layer) -> Optional["PageDispatcher"]:
    """The fleet dispatcher when this deployment has peer nodes wired
    (server boot sets layer.migration_peers), else None (single-node:
    the classic local walk)."""
    peers = getattr(layer, "migration_peers", None)
    if not peers:
        return None
    return PageDispatcher(layer, peers)


class Decommission:
    """One pool-drain driver (start fresh or resume from a checkpoint)."""

    def __init__(self, pools_layer, pool_idx: int,
                 state: Optional[dict] = None,
                 checkpoint_every: int = CHECKPOINT_EVERY):
        if not 0 <= pool_idx < len(pools_layer.pools):
            raise DecomError(f"no pool {pool_idx}")
        survivors = [i for i in range(len(pools_layer.pools))
                     if i != pool_idx
                     and i not in pools_layer.decommissioning]
        if not survivors:
            # Draining the last non-draining pool would wedge every
            # write in the cluster with nowhere to place objects.
            raise DecomError("no surviving pool to drain into")
        self.layer = pools_layer
        self.pool_idx = pool_idx
        self.checkpoint_every = checkpoint_every
        self.state = state or {
            "pool": pool_idx, "status": "draining",
            "pool_sig": pool_signature(pools_layer.pools[pool_idx]),
            "started_ns": time.time_ns(),
            "bucket": "", "marker": "",        # resume checkpoint
            "migrated": 0, "failed": 0,
            "bytes_moved": 0, "yields": 0,
        }
        # Resumed checkpoints written by older servers lack the newer
        # counters; the governor and metrics read them unconditionally.
        self.state.setdefault("bytes_moved", 0)
        self.state.setdefault("yields", 0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gov = MigrationGovernor(pools_layer, self.state, self._stop)
        self._lease = None
        # The decom document, loaded once: checkpoints must not pay a
        # cluster-wide read + scrub every few objects on the hot path.
        self._doc: Optional[dict] = None

    # -- control ---------------------------------------------------------

    def _notify_peers(self) -> None:
        """Status transitions fan out so peer nodes re-sync their
        placement-exclusion sets immediately (reference: decom updates
        ride the notification system too); checkpoint saves don't —
        they change no placement decision."""
        cb = getattr(self.layer, "on_decom_change", None)
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 - fan-out must not fail drain
                pass

    def _persist(self, scrub: bool = False) -> None:
        """Write progress using the driver's cached document — the
        checkpoint hot path must not re-read every drive in the
        cluster (the doc is only mutated by the single active drain)."""
        if self._doc is None:
            self._doc = load_doc(self.layer)
        self.state["checkpoint_ns"] = time.time_ns()
        self._doc["records"][self.state["pool_sig"]] = self.state
        self._doc["rev"] = self._doc.get("rev", 0) + 1
        _write_doc(self.layer, self._doc, self.pool_idx, scrub=scrub)

    def _acquire_lease(self) -> None:
        """Exactly ONE node drives a drain at a time: losing quorum on
        the lease mid-walk pauses this driver (checkpoint persists,
        status stays 'draining') so whichever node re-wins the lease
        resumes without two walkers racing the same keys."""
        lease = coordinator_lease(self.layer, "decom")
        if lease is not None:
            lease.on_lost = self._stop.set
            if not lease.lock(write=True, timeout=5.0):
                raise LeaseHeld(
                    "decommission coordinator lease held by another node")
        self._lease = lease

    def _release_lease(self) -> None:
        lease, self._lease = self._lease, None
        if lease is not None:
            try:
                lease.unlock()
            except Exception:  # noqa: BLE001 - lease may be lost already
                pass

    def start(self) -> None:
        self._acquire_lease()
        self.state.pop("paused", None)
        self.layer.decommissioning.add(self.pool_idx)
        try:
            self._persist(scrub=True)
        except DecomError:
            self._release_lease()
            raise
        self._notify_peers()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"decom-pool{self.pool_idx}")
        self._thread.start()

    def stop(self) -> None:
        """Pause the drain (state stays 'draining'; a resume picks up
        from the last checkpoint). Persists the current progress so a
        clean pause loses nothing — only a hard crash falls back to
        the periodic checkpoint."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._release_lease()
        if self.state.get("status") == "draining":
            # Mark the pause EXPLICIT: the elastic janitor auto-resumes
            # crashed walks (which never set this), not operator stops.
            self.state["paused"] = True
            try:
                self._persist()
            except DecomError:
                pass

    def wait(self, timeout: float = 300) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    # -- the drain -------------------------------------------------------

    def _run(self) -> None:
        try:
            self._drain()
        except Exception as e:  # noqa: BLE001 - recorded, resumable
            self.state["status"] = "failed"
            self.state["error"] = str(e)
            try:
                self._persist()
            except DecomError:
                pass
        finally:
            self._release_lease()

    def _do_key(self, bucket: str, key: str) -> None:
        """Gate on foreground pressure, then migrate one key and
        account it. Shared by the serial and parallel page paths."""
        gov = self._gov
        if not gov.gate():
            return
        try:
            moved = self._migrate_key(None, bucket, key)
            gov.count("migrated")
            gov.count("bytes_moved", int(moved or 0))
        except Exception as e:  # noqa: BLE001 - keep going
            gov.count("failed")
            self.state["last_error"] = f"{bucket}/{key}: {e}"

    def _drain(self) -> None:
        from concurrent.futures import ThreadPoolExecutor
        src = self.layer.pools[self.pool_idx]
        gov = self._gov
        since_ckpt = 0
        # Fleet-sharded walk: with peer nodes wired, each page's keys
        # spread across the cluster (coordinator aggregates counters
        # and owns EVERY checkpoint; see PageDispatcher).
        disp = page_dispatcher(self.layer)
        pool = ThreadPoolExecutor(
            max_workers=gov.workers,
            thread_name_prefix=f"decom{self.pool_idx}-mig") \
            if disp is None and gov.workers > 1 else None
        try:
            buckets = sorted(b.name for b in src.list_buckets())
            # Resume: skip buckets already fully drained.
            start_bucket = self.state.get("bucket", "")
            for bucket in buckets:
                if bucket < start_bucket:
                    continue
                marker = self.state.get("marker", "") \
                    if bucket == start_bucket else ""
                while not self._stop.is_set():
                    page = src.list_objects(bucket, marker=marker,
                                            max_keys=256,
                                            include_versions=True)
                    keys = sorted({o.name for o in page.objects})
                    if disp is not None:
                        # Fleet migration: ordered batches sharded
                        # across peer nodes, marker/checkpoint advance
                        # per completed batch.
                        for batch, agg in disp.iter_batches(
                                self.pool_idx, bucket, keys,
                                exclude={self.pool_idx}, gate=gov.gate):
                            gov.count("migrated", agg["migrated"])
                            gov.count("failed", agg["failed"])
                            gov.count("bytes_moved", agg["bytes"])
                            if agg.get("last_error"):
                                self.state["last_error"] = \
                                    agg["last_error"]
                            self.state["bucket"] = bucket
                            self.state["marker"] = batch[-1]
                            since_ckpt += len(batch)
                            if since_ckpt >= self.checkpoint_every:
                                since_ckpt = 0
                                self._persist()
                    elif pool is not None:
                        # Page-barrier parallel migration: the marker
                        # only ever advances past a FULLY completed
                        # page, so a crash re-walks at most one page
                        # (migrate_key is idempotent over re-walks).
                        list(pool.map(
                            lambda k: self._do_key(bucket, k), keys))
                        if keys and not self._stop.is_set():
                            self.state["bucket"] = bucket
                            self.state["marker"] = keys[-1]
                            since_ckpt += len(keys)
                    else:
                        for key in keys:
                            if self._stop.is_set():
                                return
                            self._do_key(bucket, key)
                            # Track progress after every key (a clean
                            # stop() persists it exactly); hit the
                            # drives only every checkpoint_every keys.
                            self.state["bucket"] = bucket
                            self.state["marker"] = key
                            since_ckpt += 1
                            if since_ckpt >= self.checkpoint_every:
                                since_ckpt = 0
                                self._persist()
                    if self._stop.is_set():
                        return
                    if since_ckpt >= self.checkpoint_every:
                        since_ckpt = 0
                        self._persist()
                    if not page.is_truncated:
                        break
                    marker = page.next_marker
                if self._stop.is_set():
                    return
                self.state["bucket"] = bucket
                self.state["marker"] = ""
                self._persist()
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        if self.state["failed"]:
            self.state["status"] = "failed"
        else:
            self.state["status"] = "complete"
            self.state["finished_ns"] = time.time_ns()
        self._persist()
        self._notify_peers()

    def _migrate_key(self, src_pool, bucket: str, key: str) -> int:
        return migrate_key(self.layer, self.pool_idx, bucket, key,
                           self._dst_idx)

    def _dst_idx(self) -> int:
        """Surviving pool with the most free space (the reference picks
        by available capacity too)."""
        best, best_free = None, -1
        for i, p in enumerate(self.layer.pools):
            if i == self.pool_idx or i in self.layer.decommissioning:
                continue
            free = p.free_space()
            if free > best_free:
                best, best_free = i, free
        if best is None:
            raise DecomError("no destination pool available")
        return best
