"""Batch jobs: bulk replicate / expire over the object namespace.

The analogue of the reference's batch framework
(cmd/batch-handlers.go:1879, cmd/batch-expire.go, docs' mc batch):
an admin submits a job document; a background worker walks the source
namespace applying the job's filters, processing each matched object
(copy to a local or remote target, or delete), with checkpointed
progress persisted on the first pool's drives so an interrupted job
resumes at boot exactly where it stopped.

Job document (JSON; the reference uses YAML — same fields):
    {"type": "replicate",
     "source": {"bucket": "b", "prefix": "p/"},
     "target": {"bucket": "dst",                 # local copy
                "endpoint": "host:port",         # or remote S3
                "accessKey": "...", "secretKey": "...", "prefix": ""},
     "filters": {"createdBefore": iso, "createdAfter": iso,
                 "tags": {"k": "v"}}}
    {"type": "expire", "source": {...}, "filters": {...}}
    {"type": "keyrotate", "source": {...}, "filters": {...},
     "encryption": {"keyId": "name"}}   # reseal SSE-S3 data keys
                                        # (reference: cmd/batch-rotate.go)
"""

from __future__ import annotations

import datetime
import json
import threading
import time
from typing import Optional

from minio_tpu.storage.local import SYS_VOL

BATCH_DIR = "config/batch"
CHECKPOINT_EVERY = 64


class BatchError(Exception):
    pass


def _parse_time(s: str) -> float:
    try:
        dt = datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except (ValueError, TypeError):
        raise BatchError(f"bad timestamp {s!r}") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.timestamp()


def validate_job(spec: dict) -> dict:
    """Normalize + validate a job document (raises BatchError)."""
    jtype = spec.get("type", "")
    if jtype not in ("replicate", "expire", "keyrotate"):
        raise BatchError(f"unknown job type {jtype!r}")
    src = spec.get("source") or {}
    if not src.get("bucket"):
        raise BatchError("source.bucket is required")
    if jtype == "replicate":
        tgt = spec.get("target") or {}
        if not tgt.get("bucket"):
            raise BatchError("target.bucket is required")
        if tgt.get("endpoint") and not (tgt.get("accessKey")
                                        and tgt.get("secretKey")):
            raise BatchError("remote target needs accessKey/secretKey")
        if not tgt.get("endpoint") and tgt["bucket"] == src["bucket"] \
                and tgt.get("prefix", "").startswith(
                    src.get("prefix", "")):
            # Copies landing inside the source listing range would be
            # re-listed and re-copied — unbounded recursive
            # amplification (x/k -> x/x/k -> ...), never terminating.
            raise BatchError("target prefix lies inside the source "
                             "listing range (recursive copy)")
    if jtype == "keyrotate" and not (spec.get("encryption")
                                     or {}).get("keyId"):
        # Without a target key the job would re-seal under the SAME
        # key and report success — a silent non-rotation.
        raise BatchError("keyrotate requires encryption.keyId")
    filters = spec.get("filters") or {}
    for k in ("createdBefore", "createdAfter"):
        if filters.get(k):
            _parse_time(filters[k])
    return spec


def _compile_filters(filters: dict) -> dict:
    """Parse filter constants ONCE per job — the walk evaluates them
    per object, and re-parsing timestamps millions of times is pure
    waste on the bulk path."""
    return {
        "before": _parse_time(filters["createdBefore"])
        if filters.get("createdBefore") else None,
        "after": _parse_time(filters["createdAfter"])
        if filters.get("createdAfter") else None,
        "tags": dict(filters.get("tags") or {}),
    }


def _match(info, compiled: dict) -> bool:
    if compiled["before"] is not None and \
            info.mod_time / 1e9 >= compiled["before"]:
        return False
    if compiled["after"] is not None and \
            info.mod_time / 1e9 <= compiled["after"]:
        return False
    if compiled["tags"]:
        import urllib.parse
        have = dict(urllib.parse.parse_qsl(info.user_tags or ""))
        for k, v in compiled["tags"].items():
            if have.get(k) != v:
                return False
    return True


class BatchJobs:
    """Job registry + workers over one object layer."""

    def __init__(self, object_layer, sets,
                 checkpoint_every: int = CHECKPOINT_EVERY):
        self.layer = object_layer
        self._sets = list(sets)
        self.checkpoint_every = checkpoint_every
        self._mu = threading.Lock()
        self._running: dict[str, threading.Thread] = {}
        self._stops: dict[str, threading.Event] = {}
        self._shutdown = False

    # -- persistence -----------------------------------------------------

    def _disks(self):
        return [d for es in self._sets for d in es.disks]

    def _save(self, state: dict) -> None:
        blob = json.dumps(state, sort_keys=True).encode()
        path = f"{BATCH_DIR}/{state['id']}.json"
        ok = 0
        for d in self._disks():
            try:
                d.write_all(SYS_VOL, path, blob)
                ok += 1
            except Exception:  # noqa: BLE001 - offline drive
                continue
        if ok < len(self._disks()) // 2 + 1:
            raise BatchError("could not persist job state to a quorum")

    def _load(self, job_id: str) -> Optional[dict]:
        votes: dict[bytes, int] = {}
        for d in self._disks():
            try:
                blob = d.read_all(SYS_VOL, f"{BATCH_DIR}/{job_id}.json")
                votes[blob] = votes.get(blob, 0) + 1
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        if not votes:
            return None
        try:
            return json.loads(max(votes.items(), key=lambda kv: kv[1])[0])
        except ValueError:
            return None

    def list_jobs(self) -> list[dict]:
        ids = set()
        for d in self._disks():
            try:
                for name in d.list_dir(SYS_VOL, BATCH_DIR):
                    if name.endswith(".json"):
                        ids.add(name[:-len(".json")])
            except Exception:  # noqa: BLE001 - absent / offline
                continue
        out = []
        for jid in sorted(ids):
            st = self.status(jid)
            if st:
                out.append(st)
        return out

    def status(self, job_id: str) -> Optional[dict]:
        st = self._load(job_id)
        if st:
            # Never echo remote credentials back through admin APIs.
            tgt = (st.get("spec") or {}).get("target")
            if tgt:
                tgt.pop("secretKey", None)
        return st

    # -- control ---------------------------------------------------------

    def start(self, spec: dict) -> str:
        from minio_tpu.storage.meta import new_uuid
        validate_job(spec)
        self.layer.get_bucket_info(spec["source"]["bucket"])
        job_id = new_uuid()[:16]
        state = {"id": job_id, "spec": spec, "status": "running",
                 "started_ns": time.time_ns(),
                 "marker": "", "processed": 0, "failed": 0}
        self._save(state)
        self._spawn(state)
        return job_id

    def resume_all(self) -> int:
        """Boot-time: restart every job that was mid-run."""
        n = 0
        for st in self.list_jobs():
            if st.get("status") == "running" and \
                    st["id"] not in self._running:
                full = self._load(st["id"])   # status() strips secrets
                if full:
                    self._spawn(full)
                    n += 1
        return n

    def cancel(self, job_id: str) -> None:
        """Stop a job. With a live worker, the WORKER persists the
        cancelled status on exit (single writer — persisting here would
        race its checkpoint saves and could be clobbered back to
        'running'); without one (crashed node), persist directly."""
        st = self._load(job_id)
        if st is None:
            raise BatchError(f"no such job {job_id!r}")
        ev = self._stops.get(job_id)
        t = self._running.get(job_id)
        if ev is not None and t is not None and t.is_alive():
            ev.set()
            return
        if st.get("status") == "running":
            st["status"] = "cancelled"
            self._save(st)

    def shutdown(self) -> None:
        """Server shutdown: stop every worker WITHOUT changing job
        statuses — interrupted jobs stay 'running' on disk so the next
        boot resumes them from their checkpoints (cancel() is the
        user-intent path that persists 'cancelled')."""
        self._shutdown = True
        with self._mu:
            events = list(self._stops.values())
            threads = list(self._running.values())
        for ev in events:
            ev.set()
        for t in threads:
            t.join(timeout=10)

    def wait(self, job_id: str, timeout: float = 300) -> bool:
        t = self._running.get(job_id)
        if t is not None:
            t.join(timeout)
            return not t.is_alive()
        return True

    def _spawn(self, state: dict) -> None:
        ev = threading.Event()
        t = threading.Thread(target=self._run, args=(state, ev),
                             daemon=True, name=f"batch-{state['id']}")
        with self._mu:
            self._stops[state["id"]] = ev
            self._running[state["id"]] = t
        t.start()

    # -- execution -------------------------------------------------------

    def _run(self, state: dict, stop: threading.Event) -> None:
        try:
            self._walk(state, stop)
        except Exception as e:  # noqa: BLE001 - recorded, resumable
            state["status"] = "failed"
            state["error"] = str(e)
            try:
                self._save(state)
            except BatchError:
                pass
        finally:
            # Finished workers prune their registry entries — a long-
            # lived server running periodic jobs must not accumulate
            # dead Thread/Event objects without bound.
            with self._mu:
                self._running.pop(state["id"], None)
                self._stops.pop(state["id"], None)

    def _walk(self, state: dict, stop: threading.Event) -> None:
        spec = state["spec"]
        src = spec["source"]
        filters = _compile_filters(spec.get("filters") or {})
        marker = state.get("marker", "")
        since_ckpt = 0
        from minio_tpu.object.types import (MethodNotAllowed,
                                            ObjectNotFound)
        while not stop.is_set():
            page = self.layer.list_objects(
                src["bucket"], prefix=src.get("prefix", ""),
                marker=marker, max_keys=256)
            for o in page.objects:
                if stop.is_set():
                    break
                try:
                    info = self.layer.get_object_info(src["bucket"],
                                                      o.name)
                    if _match(info, filters):
                        self._process(spec, src["bucket"], o.name)
                        state["processed"] += 1
                except (ObjectNotFound, MethodNotAllowed):
                    # Gone (or marker-topped) since the listing — the
                    # normal case when a crash-resume re-walks keys an
                    # expire job already deleted. A skip, NOT a failure.
                    pass
                except Exception as e:  # noqa: BLE001 - keep going
                    state["failed"] += 1
                    state["last_error"] = f"{o.name}: {e}"
                state["marker"] = o.name
                since_ckpt += 1
                if since_ckpt >= self.checkpoint_every:
                    since_ckpt = 0
                    self._save(state)
            if not page.is_truncated:
                break
            marker = page.next_marker
        if stop.is_set():
            # Single writer for the final status: the worker records
            # the outcome (cancel() only signals). A server SHUTDOWN
            # keeps the job 'running' on disk — the next boot resumes
            # it; only a user cancel persists 'cancelled'.
            if not self._shutdown:
                state["status"] = "cancelled"
            self._save(state)
            return
        state["status"] = "complete" if not state["failed"] else "failed"
        state["finished_ns"] = time.time_ns()
        self._save(state)

    def _rotate_key(self, spec: dict, bucket: str, key: str) -> None:
        """Re-seal one SSE-S3 object's data key (reference:
        cmd/batch-rotate.go rotates the object encryption key in
        place — object bytes never move)."""
        from minio_tpu.crypto import sse as sse_mod
        from minio_tpu.crypto.kms import KMS, KeyStore, KMSError
        from minio_tpu.object.types import GetOptions
        kms = getattr(self, "kms", None) or KMS.from_env()
        if kms is None:
            raise BatchError("keyrotate requires a configured KMS")
        if getattr(kms, "_keystore", None) is None:
            # Load the drive-persisted named keys (admin-created
            # rotation targets) into this KMS instance.
            try:
                KeyStore(kms, self._disks())
            except KMSError:
                pass
        kid = (spec.get("encryption") or {}).get("keyId", "")
        ctx = {"bucket": bucket, "object": key}
        # EVERY version re-seals, not just the latest — the point of
        # rotation is retiring the old master, and an Enabled-era
        # version left under it would become unreadable (or stay
        # exposed) the day it goes.
        for fi in self.layer.list_versions_all(bucket, key):
            if fi.deleted:
                continue
            imeta = {k: v for k, v in (fi.metadata or {}).items()
                     if k.startswith("x-internal-")}
            if imeta.get(sse_mod.META_ALG) != sse_mod.ALG_SSE_S3:
                continue           # plaintext / SSE-C versions skip
            data_key = kms.unseal(imeta.get(sse_mod.META_KEY, ""), ctx)
            new_sealed = kms.seal(data_key, ctx, kid=kid)
            self.layer.update_version_metadata(
                bucket, key, fi.version_id,
                lambda m, s=new_sealed: m.__setitem__(
                    sse_mod.META_KEY, s))

    def _process(self, spec: dict, bucket: str, key: str) -> None:
        from minio_tpu.object.types import (DeleteOptions, GetOptions,
                                            PutOptions)
        if spec["type"] == "keyrotate":
            return self._rotate_key(spec, bucket, key)
        if spec["type"] == "expire":
            versioned = bool(self.layer.get_bucket_meta(bucket)
                             .get("versioning"))
            self.layer.delete_object(bucket, key,
                                     DeleteOptions(versioned=versioned))
            return
        tgt = spec["target"]
        info, data = self.layer.get_object(bucket, key, GetOptions())
        dst_key = tgt.get("prefix", "") + key
        if tgt.get("endpoint"):
            from minio_tpu.s3.client import RemoteS3
            headers = {}
            if info.content_type:
                headers["content-type"] = info.content_type
            for mk, mv in info.user_metadata.items():
                headers[f"x-amz-meta-{mk}"] = mv
            RemoteS3(tgt["endpoint"], tgt["accessKey"],
                     tgt["secretKey"]).put_object(
                tgt["bucket"], dst_key, data, headers=headers)
            return
        opts = PutOptions(
            versioned=bool(self.layer.get_bucket_meta(tgt["bucket"])
                           .get("versioning")),
            user_metadata=dict(info.user_metadata),
            content_type=info.content_type,
            tags=info.user_tags)
        self.layer.put_object(tgt["bucket"], dst_key, data, opts)
